"""Durable WAL + automatic leader failover, verified by crash injection.

Fast tests drive the promotion protocol in-process: leader death, the
deterministic election (longest replicated WAL, ties to the lowest
node id), in-place promotion over the on-disk WAL mirror, term-fenced
rejection of zombies, and survivor repointing.

The `slow`-marked kill-9 torture suite runs REAL child server
processes and SIGKILLs the leader mid-workload / mid-commit /
mid-WAL-append at env-armed failpoints (util/failpoint.py
TIDB_TPU_FAILPOINTS), asserting the invariants the README's
"Durability & failover" section promises: no acknowledged-commit loss
under sync-log=commit, promotion within the election window, fencing
against the deposed epoch, and idempotent recovery across repeated
kills (reference: TiDB survives exactly this via Raft-replicated
regions, Huang et al. VLDB 2020; Ongaro & Ousterhout 2014).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

sys.path.insert(0, os.path.dirname(__file__))
from mysql_client import MiniClient, MySQLError  # noqa: E402

from tidb_tpu.rpc.client import RpcClient, RpcOptions  # noqa: E402
from tidb_tpu.rpc.errors import RPCError, StaleTermError  # noqa: E402
from tidb_tpu.session import Session  # noqa: E402
from tidb_tpu.store.storage import Storage  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# tight lease so leader loss is detected fast; election disabled by
# default (tests that want automatic failover opt in)
OPTS = RpcOptions(connect_timeout_ms=500, request_timeout_ms=2000,
                  backoff_budget_ms=1500, lock_budget_ms=8000,
                  lease_ms=1000)


def _cluster(tmp_path, n_followers=2, election_ms=0):
    leader = Storage(str(tmp_path / "leader"), shared=True,
                     rpc_listen="127.0.0.1:0", rpc_options=OPTS)
    followers = []
    for i in range(n_followers):
        opts = RpcOptions(**{**OPTS.__dict__,
                             "election_timeout_ms": election_ms})
        followers.append(Storage(
            str(tmp_path / f"f{i}"),
            remote=f"127.0.0.1:{leader.rpc_server.port}",
            rpc_options=opts))
    return leader, followers


# ==================== fast, in-process protocol tests ====================

def test_manual_promotion_preserves_acked_commits(tmp_path):
    leader, (fa, fb) = _cluster(tmp_path)
    try:
        sl, sa, sb = Session(leader), Session(fa), Session(fb)
        sl.execute("create table t (id bigint primary key, v bigint)")
        for i in range(10):
            sa.execute(f"insert into t values ({i}, {i * 10})")
        assert sb.execute("select count(*) from t").rows == [(10,)]
        old_term = fa._rpc_client.term
        from tidb_tpu.rpc.diag import cluster_members
        cluster_members(fa), cluster_members(fb)  # warm the voter roll
        # leader dies without ceremony
        leader.rpc_server.close()
        addr = fa.promote_to_leader(listen="127.0.0.1:0")
        assert fa.rpc_server.term == old_term + 1
        assert not fa.remote and fa.shared
        # every commit acked through the follower survived promotion
        assert sa.execute("select count(*) from t").rows == [(10,)]
        # writes resume on the new leader...
        sa.execute("insert into t values (100, 1000)")
        # ...and on the repointed survivor
        fb.repoint_leader(addr, fa.rpc_server.term)
        sb.execute("insert into t values (101, 1010)")
        assert sa.execute("select count(*) from t").rows == [(12,)]
        assert sb.execute("select count(*) from t").rows == [(12,)]
    finally:
        fb.close()
        fa.close()
        leader.close()


def test_automatic_election_and_repoint(tmp_path):
    """Leader loss alone must resolve the cluster: the follower with
    the longest replicated WAL promotes within the election window and
    the other follower repoints — no operator in the loop."""
    leader, (fa, fb) = _cluster(tmp_path, election_ms=1500)
    try:
        sl, sa, sb = Session(leader), Session(fa), Session(fb)
        sl.execute("create table t (id bigint primary key, v bigint)")
        sa.execute("insert into t values (1, 1)")
        assert sb.execute("select v from t").rows == [(1,)]
        time.sleep(1.2)  # a failover tick refreshes the voter roll
        leader.rpc_server.close()
        def _promoted(st):
            return not st.remote and st.rpc_server is not None

        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if _promoted(fa) or _promoted(fb):
                break
            time.sleep(0.25)
        promoted = fa if _promoted(fa) else fb
        survivor = fb if promoted is fa else fa
        assert _promoted(promoted), "no follower promoted in time"
        assert promoted.rpc_server.term == 2
        # the survivor repoints (its own manager adopts the new leader)
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            if survivor._rpc_client.term == 2 and \
                    not survivor._rpc_client.degraded:
                break
            time.sleep(0.25)
        sp = Session(promoted)
        ss = Session(survivor)
        sp.execute("insert into t values (2, 2)")
        ss.execute("insert into t values (3, 3)")
        assert sp.execute("select count(*) from t").rows == [(3,)]
        assert ss.execute("select count(*) from t").rows == [(3,)]
        h = promoted.transport_health()
        assert h["mode"] == "socket-leader" and h["term"] == 2
    finally:
        fb.close()
        fa.close()
        leader.close()


def test_stale_term_mutations_fenced(tmp_path):
    """A zombie of the old epoch — any client still carrying the
    deposed term — has its mutation attempts rejected typed."""
    leader, (fa,) = _cluster(tmp_path, n_followers=1)
    try:
        sl = Session(leader)
        sl.execute("create table t (id bigint primary key)")
        from tidb_tpu.rpc.diag import cluster_members
        cluster_members(fa)
        leader.rpc_server.close()
        addr = fa.promote_to_leader(listen="127.0.0.1:0")
        zombie = RpcClient(addr, OPTS)
        try:
            zombie.call("hello")
            zombie.term = 1  # the dead leader's epoch
            with pytest.raises(StaleTermError):
                zombie.call("lock_acquire", name="mutation", term=1)
            with pytest.raises(StaleTermError):
                zombie.call("wal_append", seq=1, expected=0,
                            data=b"x", token=0, term=1)
        finally:
            zombie.close()
    finally:
        fa.close()
        leader.close()


def test_deposed_leader_answers_are_rejected(tmp_path):
    """A restarted OLD leader serves its stale term; a client that has
    seen the new epoch treats its answers as leader loss, not
    liveness — the other half of split-brain prevention."""
    leader = Storage(str(tmp_path / "leader"), shared=True,
                     rpc_listen="127.0.0.1:0", rpc_options=OPTS)
    try:
        client = RpcClient(f"127.0.0.1:{leader.rpc_server.port}", OPTS)
        try:
            client.call("hello")
            assert client.term == 1
            client.term = 2  # this client lived through a failover
            with pytest.raises(StaleTermError):
                client.call("hello")
        finally:
            client.close()
    finally:
        leader.close()


def test_follower_mirror_is_byte_prefix_of_leader_wal(tmp_path):
    """The promotion substrate: every follower's on-disk mirror is a
    byte-for-byte prefix of the leader's WAL, through both tailed
    replication and the follower's own publishes."""
    leader, (fa,) = _cluster(tmp_path, n_followers=1)
    try:
        sl, sa = Session(leader), Session(fa)
        sl.execute("create table m (id bigint primary key, v bigint)")
        sl.execute("insert into m values (1, 1)")
        sa.execute("insert into m values (2, 2)")  # follower publish
        sl.execute("insert into m values (3, 3)")
        assert sa.execute("select count(*) from m").rows == [(3,)]
        with open(tmp_path / "leader" / "kv" / "wal.log", "rb") as f:
            leader_wal = f.read()
        with open(tmp_path / "f0" / "kv" / "wal.log", "rb") as f:
            mirror = f.read()
        assert len(mirror) > 0
        assert leader_wal[:len(mirror)] == mirror
    finally:
        fa.close()
        leader.close()


def test_torn_wal_tail_truncates_cleanly(tmp_path):
    """Garbage (a half-written record) at the WAL tail must truncate at
    recovery, not hide or corrupt the committed prefix."""
    p = str(tmp_path / "db")
    st = Storage(p, sync_log="commit")
    s = Session(st)
    s.execute("create table t (id bigint primary key, v bigint)")
    for i in range(5):
        s.execute(f"insert into t values ({i}, {i})")
    st.kv.kv.close()  # crash without checkpoint
    wal = os.path.join(p, "kv", "wal.log")
    size = os.path.getsize(wal)
    with open(wal, "ab") as f:
        f.write(b"\x01\x02" + b"\xff" * 7)  # torn header + junk
    st2 = Storage(p)
    s2 = Session(st2)
    assert s2.query("select count(*) from t") == [(5,)]
    assert os.path.getsize(wal) <= size  # torn tail gone
    s2.execute("insert into t values (100, 100)")  # log still appendable
    st2.kv.kv.close()
    st3 = Storage(p)
    assert Session(st3).query("select count(*) from t") == [(6,)]
    st3.close()


def test_corrupt_epoch_snapshot_refolds_from_kv(tmp_path):
    """A half-written columnar epoch snapshot degrades to a refold from
    the KV truth instead of poisoning recovery."""
    p = str(tmp_path / "db")
    st = Storage(p)
    s = Session(st)
    s.execute("create table e (id bigint primary key, v bigint)")
    s.execute("insert into e values (1, 10), (2, 20)")
    tid = st.catalog.table("test", "e").id
    st.close()  # checkpoint writes the epoch snapshot
    epoch = os.path.join(p, "epochs", f"t{tid}.npz")
    assert os.path.exists(epoch)
    with open(epoch, "wb") as f:
        f.write(b"PK\x03\x04 this is not a real archive")
    st2 = Storage(p)
    assert Session(st2).query("select id, v from e order by id") == \
        [(1, 10), (2, 20)]
    st2.close()


def test_heartbeat_thread_joined_on_close(tmp_path):
    """RpcClient.close() must wake AND join the heartbeat thread (the
    accept-waking pattern the listeners use) — extends the
    no-leaked-threads contract to the keepalive."""
    import threading

    def hb_threads():
        return [t for t in threading.enumerate()
                if t.is_alive() and t.name == "titpu-rpc-heartbeat"]

    leader, (fa,) = _cluster(tmp_path, n_followers=1)
    try:
        assert hb_threads(), "follower heartbeat not running"
        # the hard case: the leader is unreachable, so a beat may be
        # blocked mid-connect/recv when close() lands
        leader.rpc_server.close()
        time.sleep(0.3)
        fa.close()
        deadline = time.monotonic() + 6.0
        while hb_threads() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert hb_threads() == [], "heartbeat thread leaked past close()"
    finally:
        try:
            fa.close()
        except Exception:  # noqa: BLE001 — already closed above
            pass
        leader.close()


# ==================== the kill-9 torture harness (slow) ====================

SERVER_SRC = """
import json, os, sys, time
sys.path.insert(0, {repo!r})
from tidb_tpu.server.server import Server
from tidb_tpu.store.storage import Storage
from tidb_tpu.rpc.client import RpcOptions

kw = json.loads(os.environ["TIDB_TPU_TEST_STORAGE"])
opts = kw.pop("rpc_options", None)
if opts is not None:
    kw["rpc_options"] = RpcOptions(**opts)
storage = Storage(**kw)
srv = Server(storage, host="127.0.0.1", port=0,
             status_port=0, status_host="127.0.0.1")
srv.start()
coord = storage.rpc_server.address if storage.rpc_server else ""
print(f"PORT={{srv.port}} STATUS={{srv.status_port}} COORD={{coord}}",
      flush=True)
while True:
    time.sleep(0.5)
"""


def _spawn_server(storage_kw: dict, failpoints: str = ""):
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "TIDB_TPU_TEST_STORAGE": json.dumps(storage_kw)}
    if failpoints:
        env["TIDB_TPU_FAILPOINTS"] = failpoints
    proc = subprocess.Popen(
        [sys.executable, "-c", SERVER_SRC.format(repo=REPO)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env)
    deadline = time.time() + 180
    info = {}
    while time.time() < deadline:
        line = proc.stdout.readline()
        if line.startswith("PORT="):
            for tok in line.split():
                k, _, v = tok.partition("=")
                info[k.lower()] = v
            break
        if proc.poll() is not None:
            raise RuntimeError("server died during startup")
    assert info, "server did not report its ports"
    return proc, int(info["port"]), int(info["status"]), info["coord"]


def _status(status_port: int) -> dict:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{status_port}/status", timeout=10) as r:
        return json.load(r)


def _eventually(fn, timeout_s: float = 30.0, desc: str = ""):
    """Retry `fn` until it stops raising (MySQLError/AssertionError) —
    follower replication is ASYNC and the first statements after a
    failover may pay one backoff budget against a busy new leader."""
    deadline = time.time() + timeout_s
    while True:
        try:
            return fn()
        except (MySQLError, ConnectionError, OSError,
                AssertionError) as e:
            if time.time() >= deadline:
                raise AssertionError(f"{desc or 'condition'} not "
                                     f"reached in {timeout_s}s: {e}")
            time.sleep(0.5)


def _reap(procs):
    for p in procs:
        if p.poll() is None:
            p.terminate()
    for p in procs:
        try:
            p.wait(timeout=15)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait(timeout=15)


FOLLOWER_OPTS = dict(connect_timeout_ms=500, request_timeout_ms=2000,
                     backoff_budget_ms=1500, lock_budget_ms=8000,
                     lease_ms=1000, election_timeout_ms=3000)


@pytest.mark.slow
def test_kill9_leader_failover_end_to_end(tmp_path):
    """THE acceptance chaos test: SIGKILL the leader process
    mid-workload under sync-log=commit; a follower must promote within
    the election window with a bumped term, every acknowledged commit
    must be present, writes must resume on both survivors, and the
    restarted old leader must rejoin as a follower with its stale-term
    mutations rejected."""
    procs = []
    try:
        lp, lport, lstatus, lcoord = _spawn_server(
            {"path": str(tmp_path / "leader"), "shared": True,
             "rpc_listen": "127.0.0.1:0", "sync_log": "commit",
             "rpc_options": {**FOLLOWER_OPTS, "election_timeout_ms": 0}})
        procs.append(lp)
        fkw = {"remote": lcoord, "sync_log": "commit",
               "rpc_options": FOLLOWER_OPTS}
        ap, aport, astatus, _ = _spawn_server(
            {**fkw, "path": str(tmp_path / "fa")})
        procs.append(ap)
        bp, bport, bstatus, _ = _spawn_server(
            {**fkw, "path": str(tmp_path / "fb")})
        procs.append(bp)

        cl = MiniClient("127.0.0.1", lport)
        ca = MiniClient("127.0.0.1", aport)
        cb = MiniClient("127.0.0.1", bport)
        cl.execute("create table t (id bigint primary key, v bigint)")
        # warm both followers' voter rolls (and replication)
        assert ca.query("select count(*) from t") == [("0",)]
        assert cb.query("select count(*) from t") == [("0",)]

        # ---- phase 1: workload through follower A, kill the leader --
        acked = []
        for i in range(15):
            ca.execute(f"insert into t values ({i}, {i})")
            acked.append(i)
        time.sleep(1.5)  # a failover tick refreshes the voter roll
        os.kill(lp.pid, signal.SIGKILL)
        lp.wait(timeout=30)

        # writes fail during the outage, then resume once a follower
        # promotes — all within the election window plus slack
        t0 = time.time()
        next_id = 100
        resumed = False
        while time.time() - t0 < 90:  # election window + loaded-CI slack
            try:
                ca.execute(
                    f"insert into t values ({next_id}, {next_id})")
                acked.append(next_id)
                next_id += 1
                if resumed:
                    break
                resumed = True  # one more to prove it's stable
            except (MySQLError, ConnectionError, OSError):
                time.sleep(0.5)
        assert resumed, "writes never resumed after leader kill"

        # exactly one survivor serves as the promoted leader, term 2 —
        # polled: the loser's repoint may trail the winner's promotion
        deadline = time.time() + 30
        while True:
            roles = {}
            terms = {}
            for name, sport in (("a", astatus), ("b", bstatus)):
                st = _status(sport)["transport"]
                roles[name] = st["mode"]
                terms[name] = st.get("term", 0)
            if sorted(roles.values()) == \
                    ["socket-follower", "socket-leader"] and \
                    all(t >= 2 for t in terms.values()):
                break
            assert time.time() < deadline, (roles, terms)
            time.sleep(0.5)

        # every acknowledged commit is present on BOTH survivors (the
        # repointed loser catches up asynchronously)
        def _check_acked(c):
            got = {int(r[0]) for r in c.query("select id from t")}
            missing = set(acked) - got
            assert not missing, f"acked commits lost: {missing}"

        for c in (ca, cb):
            _eventually(lambda: _check_acked(c), 30,
                        "acked commits on survivor")

        new_leader_status = astatus if roles["a"] == "socket-leader" \
            else bstatus
        new_coord = _status(new_leader_status)["transport"]["address"]

        # ---- phase 2: the old leader returns as a FOLLOWER ----------
        rp, rport, rstatus, _ = _spawn_server(
            {"path": str(tmp_path / "leader-reborn"),
             "remote": new_coord, "sync_log": "commit",
             "rpc_options": FOLLOWER_OPTS})
        procs.append(rp)
        cr = MiniClient("127.0.0.1", rport)

        def _check_rejoin():
            got = {int(r[0]) for r in cr.query("select id from t")}
            assert set(acked) <= got, "rejoined follower missing commits"

        _eventually(_check_rejoin, 30, "rejoined follower catch-up")
        assert _status(rstatus)["transport"]["mode"] == "socket-follower"

        # ---- phase 3: the zombie epoch is fenced --------------------
        zombie = RpcClient(new_coord, OPTS)
        try:
            zombie.call("hello")
            zombie.term = 1
            with pytest.raises(StaleTermError):
                zombie.call("lock_acquire", name="mutation", term=1)
        finally:
            zombie.close()

        for c in (ca, cb, cr):
            c.close()
        cl.close()
    finally:
        _reap(procs)


CRASH_SRC = """
import os, sys
sys.path.insert(0, {repo!r})
from tidb_tpu.store.storage import Storage
from tidb_tpu.session import Session
st = Storage({path!r}, sync_log="commit")
s = Session(st)
s.execute("create table if not exists t (id bigint primary key, v bigint)")
for i in range({start}, {start} + {count}):
    s.execute(f"insert into t values ({{i}}, {{i}})")
    print(f"ACK={{i}}", flush=True)
{epilogue}
print("DONE", flush=True)
os._exit(0)
"""


def _run_crash_child(path: str, start: int, count: int,
                     failpoints: str, epilogue: str = "") -> list[int]:
    """Run a workload child until it exits (crash or DONE); returns the
    ids it ACKED before dying."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "TIDB_TPU_FAILPOINTS": failpoints}
    proc = subprocess.Popen(
        [sys.executable, "-c", CRASH_SRC.format(
            repo=REPO, path=path, start=start, count=count,
            epilogue=epilogue)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env)
    acked = []
    try:
        for line in proc.stdout:
            if line.startswith("ACK="):
                acked.append(int(line.strip().split("=")[1]))
    finally:
        proc.wait(timeout=120)
    return acked


@pytest.mark.slow
@pytest.mark.parametrize("failpoints,epilogue", [
    # kill-9 mid-WAL-append: half a record on disk (torn tail)
    ("kv/wal-torn-append=exit(9)@40", ""),
    # kill-9 mid-commit: KV committed, columnar fold never ran
    ("storage/before-fold=exit(9)@12", ""),
    # kill-9 mid-checkpoint: some epochs persisted, WAL not yet folded
    ("storage/mid-checkpoint=exit(9)@1", "st.checkpoint()"),
    # kill-9 mid-GROUP-fsync: the elected leader dies with the batch's
    # bytes flushed to the OS but not fsynced — nothing in that batch
    # was acked, so recovery owes none of it (and loses none acked)
    ("kv/group-fsync=exit(9)@40", ""),
])
def test_kill9_no_acked_commit_loss(tmp_path, failpoints, epilogue):
    """sync-log=commit contract under SIGKILL at every storage-path
    failpoint: every acknowledged insert survives recovery, the store
    reopens clean, and stays writable."""
    p = str(tmp_path / "db")
    acked = _run_crash_child(p, 0, 200, failpoints, epilogue)
    assert acked, "child crashed before acking anything"
    st = Storage(p)
    s = Session(st)
    got = {r[0] for r in s.query("select id from t")}
    missing = set(acked) - got
    assert not missing, \
        f"acked commits lost under {failpoints}: {sorted(missing)}"
    s.execute("insert into t values (9999, 9999)")
    assert 9999 in {r[0] for r in s.query("select id from t")}
    st.close()


CONCURRENT_CRASH_SRC = """
import os, sys, threading
sys.path.insert(0, {repo!r})
from tidb_tpu.store.storage import Storage
from tidb_tpu.session import Session
st = Storage({path!r}, sync_log="commit")
boot = Session(st)
boot.execute("create table if not exists t (id bigint primary key, v bigint)")
print_lock = threading.Lock()
def writer(w):
    s = Session(st)
    for j in range({per}):
        i = {start} + w * {per} + j
        s.execute(f"insert into t values ({{i}}, {{i}})")
        with print_lock:
            print(f"ACK={{i}}", flush=True)
threads = [threading.Thread(target=writer, args=(w,))
           for w in range({writers})]
for t in threads: t.start()
for t in threads: t.join()
print("DONE", flush=True)
os._exit(0)
"""


@pytest.mark.slow
@pytest.mark.parametrize("failpoints", [
    # the group-fsync leader dies MID-RENDEZVOUS with waiters parked on
    # the condition variable — the sharpest cut through the batching
    # path: several commits' bytes written, none fsynced, none acked.
    # (This site is engine-independent — it lives in SyncPolicy — so
    # the crash fires under the native engine too, unlike the
    # python-engine-only kv/wal-torn-append site.)
    "kv/group-fsync=exit(9)@5",
    "kv/group-fsync=exit(9)@60",
])
def test_kill9_concurrent_group_commit_no_acked_loss(tmp_path,
                                                     failpoints):
    """sync-log=commit contract under CONCURRENT committers sharing
    group fsyncs: SIGKILL mid-group-fsync loses no acked commit, leaves
    no half-applied unacked commit visible, and the store reopens
    writable."""
    p = str(tmp_path / "db")
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "TIDB_TPU_FAILPOINTS": failpoints}
    proc = subprocess.Popen(
        [sys.executable, "-c", CONCURRENT_CRASH_SRC.format(
            repo=REPO, path=p, start=0, per=60, writers=8)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env)
    acked = []
    try:
        for line in proc.stdout:
            if line.startswith("ACK="):
                acked.append(int(line.strip().split("=")[1]))
    finally:
        proc.wait(timeout=120)
    assert acked, "child crashed before acking anything"
    assert proc.returncode != 0, "failpoint never fired"
    st = Storage(p)
    s = Session(st)
    rows = s.query("select id, v from t order by id")
    got = {r[0] for r in rows}
    missing = set(acked) - got
    assert not missing, \
        f"acked commits lost under {failpoints}: {sorted(missing)[:10]}"
    # consistency of whatever IS present (acked or in-flight at the
    # kill): full rows, no duplicates, id==v invariant intact
    assert len(rows) == len(got)
    assert all(r[1] == r[0] for r in rows)
    # and the recovered store still takes durable writes
    s.execute("insert into t values (999999, 999999)")
    assert 999999 in {r[0] for r in s.query("select id from t")}
    st.close()


@pytest.mark.slow
def test_kill9_recovery_idempotent_across_repeated_kills(tmp_path):
    """Crash -> recover -> crash again, rotating the kill site each
    round: recovery must be idempotent (acked set only grows, no
    duplicates, no resurrection), exactly like a store that never
    crashed."""
    p = str(tmp_path / "db")
    fps = ["kv/wal-torn-append=exit(9)@30",
           "storage/before-fold=exit(9)@8",
           "kv/wal-torn-append=exit(9)@55"]
    all_acked: set[int] = set()
    start = 0
    for fp in fps:
        acked = _run_crash_child(p, start, 100, fp)
        all_acked.update(acked)
        start += 100
        st = Storage(p)
        rows = Session(st).query("select id from t order by id")
        got = [r[0] for r in rows]
        assert len(got) == len(set(got)), "duplicate handles after crash"
        missing = all_acked - set(got)
        assert not missing, f"acked commits lost at {fp}: {missing}"
        st.close()  # a CLEAN close between kills: checkpoint must not
        #             resurrect or drop anything either
    assert len(all_acked) > 50
