"""ENUM / SET / BIT / JSON types + case-insensitive collations.

Counterpart of the reference's extended type surface (reference:
types/enum.go, types/set.go, types/json/binary.go,
expression/builtin_json.go, util/collate/collate.go:62)."""

from __future__ import annotations

import pytest

from tidb_tpu.session import Session
from tidb_tpu.server.errors import (ER_INVALID_JSON_TEXT,
                                    WARN_DATA_TRUNCATED, classify)

from testkit import TestKit


@pytest.fixture()
def tk():
    k = TestKit()
    k.must_exec(
        "create table t (id int primary key, "
        "st enum('small','medium','large'), perms set('r','w','x'), "
        "flags bit(8), doc json, "
        "name varchar(20) collate utf8mb4_general_ci)")
    k.must_exec(
        "insert into t values "
        "(1, 'small', 'r,w', b'1010', '{\"a\": 1, \"b\": [1,2,3]}', "
        "'Alice'), "
        "(2, 'LARGE', 'x', 5, '{\"a\": 2}', 'BOB'), "
        "(3, 'medium', '', 0, '[10, 20]', 'alice')")
    return k


def test_enum_storage_and_definition_order(tk):
    # ENUM renders the defined spelling (ci input accepted) and sorts by
    # definition index, not lexicographically
    rows = tk.must_query("select id, st from t order by st, id")
    assert [r[1] for r in rows] == ["small", "medium", "large"]
    assert tk.must_query("select id from t where st = 'large'") == [(2,)]
    with pytest.raises(Exception, match="Data truncated"):
        tk.must_exec("insert into t values (9,'huge','',0,'{}','x')")


def test_set_bitmask_semantics(tk):
    assert tk.must_query("select perms from t order by id") == \
        [("r,w",), ("x",), ("",)]
    # order-insensitive membership equality
    assert tk.must_query("select id from t where perms = 'w,r'") == [(1,)]
    assert tk.must_query("select id from t where perms = ''") == [(3,)]
    assert tk.must_query(
        "select id, find_in_set('x', perms) from t order by id") == \
        [(1, 0), (2, 1), (3, 0)]
    with pytest.raises(Exception, match="Data truncated"):
        tk.must_exec("insert into t values (9,'small','rwx',0,'{}','x')")


def test_bit_type(tk):
    assert tk.must_query("select id from t where flags = 10") == [(1,)]
    assert tk.must_query("select flags + 1 from t where id = 2") == [(6,)]
    with pytest.raises(Exception, match="out of range"):
        tk.must_exec("insert into t values (9,'small','',256,'{}','x')")


def test_json_extract_and_operators(tk):
    assert tk.must_query(
        "select id, doc->'$.a', doc->>'$.a' from t order by id") == \
        [(1, "1", "1"), (2, "2", "2"), (3, None, None)]
    assert tk.must_query(
        "select id from t where doc->'$.b[1]' = '2'") == [(1,)]
    assert tk.must_query(
        "select json_length(doc), json_type(doc) from t order by id") == \
        [(2, "OBJECT"), (1, "OBJECT"), (2, "ARRAY")]
    assert tk.must_query(
        "select json_valid('{\"x\": 1}'), json_valid('nope')") == [(1, 0)]
    # string results unquote through ->>
    tk.must_exec(
        "insert into t values (4,'small','',0,'{\"s\": \"hi\"}','x')")
    assert tk.must_query(
        "select doc->'$.s', doc->>'$.s' from t where id = 4") == \
        [('"hi"', "hi")]
    # normalization: key order doesn't matter for equality
    tk.must_exec(
        "insert into t values (5,'small','',0,'{\"b\":2,\"a\":1}','x')")
    tk.must_exec(
        "insert into t values (6,'small','',0,'{\"a\":1,\"b\":2}','x')")
    assert tk.must_query(
        "select count(*) from t t1 join t t2 on t1.doc = t2.doc "
        "where t1.id >= 5") == [(4,)]
    with pytest.raises(Exception, match="Invalid JSON"):
        tk.must_exec("insert into t values (9,'small','',0,'oops','x')")


def test_json_object_array_constructors():
    tk = TestKit()
    assert tk.must_query("select json_array(1, 2, 'x')") == \
        [('[1, 2, "x"]',)]
    r = tk.must_query("select json_object('k', 1, 'j', 'v')")
    assert r == [('{"j": "v", "k": 1}',)]


def test_ci_collation_compare_group_join(tk):
    assert tk.must_query(
        "select id from t where name = 'ALICE' order by id") == \
        [(1,), (3,)]
    assert tk.must_query(
        "select count(*) from t where name like 'a%'") == [(2,)]
    grouped = tk.must_query(
        "select count(*) from t where id <= 3 group by name "
        "order by count(*) desc")
    assert grouped == [(2,), (1,)]
    assert tk.must_query(
        "select count(*) from t a join t b on a.name = b.name "
        "where a.id <= 3 and b.id <= 3") == [(5,)]
    # IN-lists honor ci
    assert tk.must_query(
        "select count(*) from t where name in ('ALICE', 'zed')") == [(2,)]
    # ORDER BY is case-insensitive (ties keep row order)
    names = [r[0] for r in tk.must_query(
        "select name from t where id <= 3 order by name, id")]
    assert names == ["Alice", "alice", "BOB"]


def test_json_literal_equality_roundtrip(tk):
    # un-normalized literal spelling must match the normalized storage
    tk.must_exec('insert into t values (7,\'small\',\'\',0,'
                 '\'{"x":1}\',\'q\')')
    assert tk.must_query(
        'select id from t where doc = \'{"x":1}\'') == [(7,)]
    assert tk.must_query(
        'select id from t where doc = \'{ "x" : 1 }\'') == [(7,)]


def test_ci_min_max(tk):
    # MIN/MAX honor the ci collation (casefold order), not code order
    assert tk.must_query(
        "select min(name), max(name) from t where id <= 3") == \
        [("Alice", "BOB")]


def test_bit_width_limits():
    tk2 = TestKit()
    tk2.must_exec("create table bw (f bit(63))")
    big = (1 << 63) - 1
    tk2.must_exec(f"insert into bw values ({big})")
    assert tk2.must_query("select f from bw") == [(big,)]
    with pytest.raises(Exception, match="out of range"):
        tk2.must_exec(f"insert into bw values ({1 << 63})")
    with pytest.raises(Exception, match="exceeds supported"):
        tk2.must_exec("create table bw2 (f bit(64))")


def test_binary_collation_unchanged():
    tk = TestKit()
    tk.must_exec("create table b (s varchar(10))")
    tk.must_exec("insert into b values ('A'), ('a')")
    assert tk.must_query("select count(*) from b where s = 'a'") == [(1,)]
    assert tk.must_query(
        "select count(*) from b group by s order by 1") == [(1,), (1,)]


def test_errno_mappings():
    assert classify("Data truncated: invalid ENUM value 'x'")[0] == \
        WARN_DATA_TRUNCATED
    assert classify("Invalid JSON text: 'oops'")[0] == ER_INVALID_JSON_TEXT


def test_enum_json_survive_restart(tmp_path):
    path = str(tmp_path / "store")
    from tidb_tpu.store.storage import Storage

    st = Storage(path)
    s = Session(st)
    s.execute("create table e (id int primary key, "
              "lvl enum('lo','hi'), doc json)")
    s.execute("insert into e values (1, 'hi', '{\"k\": 3}')")
    st.close()
    st2 = Storage(path)
    s2 = Session(st2)
    assert s2.execute("select lvl, doc->>'$.k' from e").rows == \
        [("hi", "3")]
    # the fixed dictionary still validates after reopen
    with pytest.raises(Exception, match="Data truncated"):
        s2.execute("insert into e values (2, 'nope', '{}')")
    st2.close()
