"""Statistics subsystem: sketches, histograms, ANALYZE, CBO access paths.

Mirrors the reference's statistics tests (statistics/cmsketch_test.go,
histogram_test.go, selectivity_test.go) plus planner integration.
"""

import numpy as np
import pytest

from tidb_tpu.session import Session
from tidb_tpu.stats import CMSketch, FMSketch, Histogram, StatsHandle


# ---------------- sketch units ----------------

def test_cmsketch_point_estimates():
    rng = np.random.default_rng(0)
    # zipf-ish: value v appears ~ 10000/v times
    vals = np.repeat(np.arange(1, 200), (10000 / np.arange(1, 200)).astype(int))
    sk = CMSketch.build(vals)
    assert abs(sk.query(1) - 10000) / 10000 < 0.05  # heavy hitter: exact-ish
    assert abs(sk.query(50) - 200) <= 200  # tail: within a bucket collision
    rare = sk.query(10**9)  # never-seen value
    assert rare <= sk.query(2)


def test_cmsketch_scaled():
    vals = np.repeat(np.arange(100), 100)
    sk = CMSketch.build(vals, scale=10.0)
    assert 500 <= sk.query(5) <= 2000  # 100 actual * 10 scale


def test_fmsketch_ndv():
    rng = np.random.default_rng(1)
    vals = rng.integers(0, 50_000, size=300_000)
    ndv = FMSketch.build(vals).ndv
    true_ndv = len(np.unique(vals))
    assert abs(ndv - true_ndv) / true_ndv < 0.15


def test_histogram_range_and_eq():
    rng = np.random.default_rng(2)
    vals = rng.integers(0, 1000, size=100_000)
    h = Histogram.build(vals)
    # uniform: [100, 200) holds ~10%
    est = h.range_count(100, 200, True, False)
    assert abs(est - 10_000) / 10_000 < 0.1
    # unbounded sides
    assert abs(h.range_count(None, 500, True, False) - 50_000) < 5_000
    assert abs(h.range_count(500, None, True, True) - 50_000) < 5_000
    # eq on a repeated upper bound is sane
    assert 0 < h.eq_count(float(vals[0])) < 1000


def test_histogram_skew():
    vals = np.concatenate([np.zeros(90_000), np.arange(1, 10_001)])
    h = Histogram.build(vals)
    assert h.range_count(0, 0, True, True) > 80_000  # the spike
    est = h.range_count(5000, None, True, True)
    assert est < 10_000


# ---------------- ANALYZE + planner integration ----------------

@pytest.fixture
def se():
    s = Session()
    s.execute("CREATE TABLE ev (id INT PRIMARY KEY, ts INT, grp INT, "
              "KEY kts (ts), KEY kgrp (grp))")
    rows = ", ".join(f"({i}, {i % 10_000}, {i % 7})" for i in range(5000))
    s.execute(f"INSERT INTO ev VALUES {rows}")
    return s


def explain(s, sql):
    return "\n".join(r[0] for r in s.query("EXPLAIN " + sql))


def test_analyze_builds_stats(se):
    se.execute("ANALYZE TABLE ev")
    ts = se.storage.stats.table_stats(
        se.catalog.table("test", "ev").id)
    assert ts is not None and ts.row_count == 5000
    cs = ts.columns[0]
    assert abs(cs.ndv - 5000) / 5000 < 0.15
    assert cs.histogram is not None


def test_interval_index_requires_stats(se):
    # without stats: comparison predicates never choose the index
    p = explain(se, "SELECT COUNT(*) FROM ev WHERE ts < 50")
    assert "index:" not in p
    se.execute("ANALYZE TABLE ev")
    # ts < 50 matches ~25/5000 rows (0.5%) -> index range scan
    p = explain(se, "SELECT COUNT(*) FROM ev WHERE ts < 50")
    assert "index:kts" in p and "range" in p
    # ts < 9000 matches ~90% -> stays a device full scan
    p = explain(se, "SELECT COUNT(*) FROM ev WHERE ts < 9000")
    assert "index:" not in p


def test_interval_scan_correctness(se):
    se.execute("ANALYZE TABLE ev")
    want = [(r,) for r in sorted(
        i for i in range(5000) if 20 <= (i % 10_000) <= 40)]
    got = se.query("SELECT id FROM ev WHERE ts >= 20 AND ts <= 40 "
                   "ORDER BY id")
    assert got == want
    # interval + residual filter
    assert se.query(
        "SELECT COUNT(*) FROM ev WHERE ts >= 20 AND ts <= 40 AND grp = 0"
    ) == [(sum(1 for i in range(5000)
               if 20 <= i % 10_000 <= 40 and i % 7 == 0),)]


def test_point_index_gated_by_stats(se):
    # grp has 7 distinct values over 5000 rows (~14% each): with stats the
    # planner must prefer the device scan over gathering ~700 rows
    se.execute("ANALYZE TABLE ev")
    p = explain(se, "SELECT COUNT(*) FROM ev WHERE grp = 3")
    assert "index:" not in p
    # correctness unchanged
    assert se.query("SELECT COUNT(*) FROM ev WHERE grp = 3") == \
        [(sum(1 for i in range(5000) if i % 7 == 3),)]


def test_explain_est_rows(se):
    se.execute("ANALYZE TABLE ev")
    p = explain(se, "SELECT COUNT(*) FROM ev WHERE ts < 50")
    assert "est=" in p


def test_auto_analyze_triggers():
    s = Session()
    s.execute("CREATE TABLE aa (id INT PRIMARY KEY, v INT, KEY kv (v))")
    rows = ", ".join(f"({i}, {i})" for i in range(200))
    s.execute(f"INSERT INTO aa VALUES {rows}")
    info = s.catalog.table("test", "aa")
    store = s.storage.table_store(info.id)
    assert s.storage.stats.needs_auto_analyze(info, store)
    analyzed = s.storage.stats.auto_analyze(s.storage, s.catalog)
    assert "aa" in analyzed
    assert not s.storage.stats.needs_auto_analyze(info, store)
    # small delta doesn't retrigger; big delta does
    s.execute("INSERT INTO aa VALUES (1000, 1)")
    assert not s.storage.stats.needs_auto_analyze(info, store)
    rows = ", ".join(f"({i}, {i})" for i in range(2000, 2200))
    s.execute(f"INSERT INTO aa VALUES {rows}")
    assert s.storage.stats.needs_auto_analyze(info, store)


def test_stats_dropped_with_table(se):
    se.execute("ANALYZE TABLE ev")
    tid = se.catalog.table("test", "ev").id
    assert se.storage.stats.table_stats(tid) is not None
    se.execute("DROP TABLE ev")
    assert se.storage.stats.table_stats(tid) is None


def test_string_eq_after_analyze():
    # code-review regression: CM sketch is keyed on dictionary codes but
    # predicates carry raw strings
    s = Session()
    s.execute("CREATE TABLE p (id INT PRIMARY KEY, name VARCHAR(5), v INT, "
              "KEY kn (name))")
    s.execute("INSERT INTO p VALUES " + ", ".join(
        f"({i}, '{'abc'[i % 3]}', {i})" for i in range(300)))
    s.execute("ANALYZE TABLE p")
    assert s.query("SELECT COUNT(*) FROM p WHERE name = 'b' AND v >= 0") \
        == [(100,)]
    assert s.query("SELECT COUNT(*) FROM p WHERE name = 'zz'") == [(0,)]


def test_cmsketch_float_heavy_hitter():
    # code-review regression: float TopN keys must not be int-truncated
    vals = np.concatenate([np.full(1000, 2.5), np.arange(100) + 0.25])
    sk = CMSketch.build(vals)
    assert sk.query(2.5) == 1000
    assert sk.query(np.float64(2.5)) == 1000


def test_histogram_strict_less_at_bucket_edge():
    # code-review regression: < at a bucket upper bound must exclude repeats
    vals = np.concatenate([np.arange(100), np.full(100, 100.0),
                           np.arange(101, 201)])
    h = Histogram.build(vals)
    less = h.range_count(None, 100, True, False)
    ge = h.range_count(100, None, True, True)
    assert abs(less - 100) < 25
    assert abs(ge - 200) < 25


def test_sampled_ndv_extrapolation():
    # code-review regression: NDV from a sampled build scales up
    from tidb_tpu.stats.handle import SAMPLE_CAP, StatsHandle
    import tidb_tpu.stats.handle as H
    old = H.SAMPLE_CAP
    H.SAMPLE_CAP = 10_000
    try:
        s = Session()
        s.execute("CREATE TABLE nx (id INT PRIMARY KEY)")
        info = s.catalog.table("test", "nx")
        store = s.storage.table_store(info.id)
        store.bulk_load([np.arange(100_000, dtype=np.int64)])
        s.execute("ANALYZE TABLE nx")
        ndv = s.storage.stats.table_stats(info.id).columns[0].ndv
        assert ndv > 50_000  # all-distinct column: sampled ndv must scale
    finally:
        H.SAMPLE_CAP = old


def test_analyze_with_nulls_and_strings():
    s = Session()
    s.execute("CREATE TABLE ns (id INT PRIMARY KEY, name VARCHAR(10), v INT)")
    s.execute("INSERT INTO ns VALUES (1,'a',10),(2,NULL,20),(3,'b',NULL),"
              "(4,'a',40)")
    s.execute("ANALYZE TABLE ns")
    ts = s.storage.stats.table_stats(s.catalog.table("test", "ns").id)
    name_stats = ts.columns[1]
    assert name_stats.null_count == 1
    assert name_stats.histogram is None  # strings: no histogram
    assert name_stats.ndv == 2
    assert ts.columns[2].null_count == 1


def test_cmsketch_rows_all_distribute():
    """Every depth row must spread values over buckets: a degenerate row
    (all mass in one bucket) wastes a min() contributor."""
    import numpy as np
    from tidb_tpu.stats.sketch import CMSketch

    vals = np.arange(5000, dtype=np.int64)
    sk = CMSketch.build(vals)
    for d in range(CMSketch.DEPTH):
        assert (sk.table[d] > 0).sum() > CMSketch.WIDTH // 4, (
            f"depth row {d} is degenerate")


def test_stats_persist_and_reload():
    """ANALYZE persists stats into the meta-KV plane; a fresh handle
    (restart analog) reloads them with estimates intact."""
    from tidb_tpu.session import Session
    from tidb_tpu.stats import StatsHandle

    s = Session()
    s.execute("CREATE TABLE sp (a INT, b VARCHAR(6))")
    s.execute("INSERT INTO sp VALUES " + ",".join(
        f"({i % 50},'v{i % 9}')" for i in range(3000)))
    s.execute("ANALYZE TABLE sp")
    tid = s.catalog.table("test", "sp").id
    before = s.storage.stats.table_stats(tid)
    est_before = s.storage.stats.est_eq_rows(tid, 0, 7, None)

    fresh = StatsHandle()
    assert fresh.table_stats(tid) is None
    n = fresh.load_from_kv(s.storage, s.catalog)
    assert n >= 1
    after = fresh.table_stats(tid)
    assert after is not None
    assert after.row_count == before.row_count
    assert fresh.est_eq_rows(tid, 0, 7, None) == est_before


def test_feedback_corrects_estimate():
    """A mis-estimated range self-corrects after execution (reference:
    statistics/feedback.go)."""
    from tidb_tpu.plan import PlanBuilder, optimize
    from tidb_tpu.plan.physical import PhysTableRead
    from tidb_tpu.session import Session
    from tidb_tpu.sql.parser import parse_one

    s = Session()
    s.execute("CREATE TABLE fb (a INT, b INT)")
    # heavily skewed: histogram buckets average the skew away
    rows = ",".join(f"({1 if i < 2950 else i},{i})" for i in range(3000))
    s.execute("INSERT INTO fb VALUES " + rows)
    s.execute("ANALYZE TABLE fb")

    def est(sql):
        plan = optimize(PlanBuilder(s.catalog, s.current_db).build_select(
            parse_one(sql)), s.storage.stats)

        def find(p):
            if isinstance(p, PhysTableRead):
                return p
            for c in p.children:
                r = find(c)
                if r is not None:
                    return r
        tr = find(plan)
        return tr.est_rows if tr is not None else None

    q = "SELECT b FROM fb WHERE a = 1"
    first = est(q)
    actual = len(s.query(q))
    assert actual == 2950
    corrected = est(q)
    assert corrected == actual, (first, corrected, actual)
