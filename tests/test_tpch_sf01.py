"""TPC-H differential at SF 0.1 (600k-row lineitem).

VERDICT item: the SF 0.003 suite validates mostly the host fallback —
at this scale the shape buckets are non-trivial, device fragments and the
high-cardinality TopN path genuinely engage, and padding is a rounding
error rather than the bulk of the data. The 7 queries cover the engine's
main shapes: scan+filter+sum (q6), multi-key dense agg (q1), join
fragments (q5/q9/q12), high-cardinality TopN (q3), and the semi-join +
group-by subquery (q18). TPCH_SF overrides the scale for manual larger
runs.
"""

import os

import pytest

from tidb_tpu.bench.tpch_data import TPCH_DDL, generate_tpch, load_table
from tidb_tpu.bench.tpch_queries import TPCH_QUERIES
from tidb_tpu.session import Session
from tpch_oracle import load_sqlite, rows_equal, to_sqlite_sql

SF = float(os.environ.get("TPCH_SF", "0.1"))
SEED = 3
QUERIES = ("q1", "q3", "q5", "q6", "q9", "q12", "q18")


@pytest.fixture(scope="module")
def tpch_sf01():
    data = generate_tpch(SF, SEED)
    session = Session()
    for name in TPCH_DDL:
        load_table(session, name, data[name])
    conn = load_sqlite(data, TPCH_DDL)
    yield session, conn
    conn.close()


@pytest.mark.parametrize("qname", QUERIES)
def test_tpch_sf01_query(tpch_sf01, qname):
    session, conn = tpch_sf01
    sql = TPCH_QUERIES[qname]
    got = session.query(sql)
    want = [tuple(r) for r in conn.execute(to_sqlite_sql(sql)).fetchall()]
    ok, msg = rows_equal(got, want, ordered=False)
    assert ok, f"{qname}: {msg}"
    assert len(got) > 0 or qname not in ("q1", "q3", "q5")
