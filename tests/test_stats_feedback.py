"""Histogram/point feedback + exponential-backoff selectivity.

Counterpart of the reference's feedback merge (statistics/feedback.go,
handle/update.go:551) and multi-predicate selectivity combination
(statistics/selectivity.go). Round-2 verdict weak #7: feedback was
scan-count-only; these tests pin the bucket/point-level corrections."""

from __future__ import annotations

import numpy as np
import pytest

from tidb_tpu.stats.histogram import Histogram

from testkit import TestKit


def test_histogram_range_feedback_rescales_buckets():
    v = np.arange(10_000, dtype=np.float64)
    h = Histogram.build(v)
    est_before = h.range_count(0, 1000, True, False)
    assert est_before == pytest.approx(1000, rel=0.05)
    # reality says that range holds 5x more rows
    h.apply_range_feedback(0, 1000, True, False, 5000)
    est_after = h.range_count(0, 1000, True, False)
    assert est_after == pytest.approx(5000, rel=0.2)
    # untouched tail unchanged
    assert h.range_count(9000, None, True, True) == \
        pytest.approx(1000, rel=0.1)


def test_histogram_feedback_clamped():
    v = np.arange(1000, dtype=np.float64)
    h = Histogram.build(v)
    h.apply_range_feedback(0, 100, True, False, 1e9)  # absurd observation
    # clamped to 10x, not 10^7x
    assert h.range_count(0, 100, True, False) <= 100 * 10 * 1.2


def test_partial_overlap_feedback_stays_local():
    """A narrow observation must not inflate the whole containing
    bucket: estimates outside the observed interval stay put."""
    # one wide bucket: skewed data all inside [0, 1000)
    v = np.concatenate([np.zeros(10), np.full(10, 999.0)])
    h = Histogram.build(v, n_buckets=1)
    before_tail = h.range_count(500, 1000, True, False)
    h.apply_range_feedback(0, 10, True, False, 100)
    after_tail = h.range_count(500, 1000, True, False)
    # the tail's estimate moves by at most the interval's share
    assert after_tail <= before_tail * 1.3


def test_eq_feedback_hot_key_does_not_churn_cache():
    from tidb_tpu.stats.handle import ColumnStats

    cs = ColumnStats(0, 10, None, None, 100.0)
    for i in range(ColumnStats.MAX_EQ_FEEDBACK):
        cs.note_eq_feedback(i, float(i))
    for _ in range(10):  # hot existing key: no eviction
        cs.note_eq_feedback(5, 55.0)
    assert len(cs.eq_feedback) == ColumnStats.MAX_EQ_FEEDBACK
    assert cs.eq_rows(0) == 0.0 and cs.eq_rows(5) == 55.0


def test_eq_feedback_overrides_sketch():
    tk = TestKit()
    tk.must_exec("create table f (a int, b int)")
    # a=1 dominates but the sketch underestimates after sampling; the
    # executed count becomes the truth
    rows = ",".join(f"(1,{i})" for i in range(500)) + "," + ",".join(
        f"({i + 2},{i})" for i in range(100))
    tk.must_exec(f"insert into f values {rows}")
    tk.must_exec("analyze table f")
    s = tk.session
    info = s.catalog.table("test", "f")
    # run the predicate: the device scan records actual counts
    assert tk.must_query("select count(*) from f where a = 1") == [(600 - 100,)]
    tk.must_query("select b from f where a = 1")
    cs = s.storage.stats.table_stats(info.id).columns[0]
    assert cs.eq_rows(1) == 500


def test_range_feedback_via_execution():
    tk = TestKit()
    tk.must_exec("create table r (a int, b int)")
    # clustered distribution the equal-depth histogram smooths over
    rows = ",".join(f"({i % 50},{i})" for i in range(3000))
    tk.must_exec(f"insert into r values {rows}")
    tk.must_exec("analyze table r")
    s = tk.session
    info = s.catalog.table("test", "r")
    before = s.storage.stats.est_range_rows(info.id, 0, 0, 10, True,
                                            False, 3000)
    tk.must_query("select b from r where a >= 0 and a < 10")
    # the histogram absorbed the observed count
    cs = s.storage.stats.table_stats(info.id).columns[0]
    est = cs.histogram.range_count(0, 10, True, False)
    assert est == pytest.approx(600, rel=0.35)


def test_backoff_selectivity_correlated_predicates():
    """Two perfectly correlated predicates: naive independence squares
    the selectivity; backoff keeps the estimate near the single-column
    truth (factor 2-3, not 10)."""
    tk = TestKit()
    tk.must_exec("create table c (a int, b int, v int)")
    rows = ",".join(f"({i % 10},{i % 10},{i})" for i in range(5000))
    tk.must_exec(f"insert into c values {rows}")
    tk.must_exec("analyze table c")
    s = tk.session
    info = s.catalog.table("test", "c")
    from tidb_tpu.plan.physical import _est_selection_rows
    from tidb_tpu.plan.builder import PlanBuilder
    from tidb_tpu.plan.expr import Call, Col, Const, bool_call
    from tidb_tpu.types.field_type import FieldType, TypeKind

    it = FieldType(TypeKind.INT)
    conds = [bool_call("eq", [Col(0, it), Const(3, it)]),
             bool_call("eq", [Col(1, it), Const(3, it)])]
    est = _est_selection_rows(info, [0, 1, 2], conds, s.storage.stats)
    truth = 500.0
    # naive product would give ~50; backoff stays within ~3x of truth
    assert est >= truth / 3.2, est
