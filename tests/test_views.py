"""CREATE/DROP VIEW + plan-time expansion (reference: ddl/ddl_api.go
CreateView; planner/core/logical_plan_builder.go
BuildDataSourceFromView)."""

import pytest

from tidb_tpu.session import Session, SQLError


@pytest.fixture()
def s():
    s = Session()
    s.execute("create table t (id bigint primary key, v bigint, g bigint)")
    s.execute("insert into t values (1,10,1),(2,20,1),(3,30,2)")
    return s


def test_view_basics(s):
    s.execute("create view vs as select g, sum(v) total from t group by g")
    assert s.query("select * from vs order by g") == [(1, 30), (2, 30)]
    assert s.query("select total from vs where g = 2") == [(30,)]


def test_view_column_list_and_join(s):
    s.execute("create view v2 (grp, tot) as select g, sum(v) from t "
              "group by g")
    got = s.query("select t.id, v2.tot from t, v2 where t.g = v2.grp "
                  "order by t.id")
    assert got == [(1, 30), (2, 30), (3, 30)]


def test_view_tracks_dml_and_nesting(s):
    s.execute("create view v1 as select g, sum(v) tot from t group by g")
    s.execute("create view v3 as select g, tot from v1 where tot > 25")
    s.execute("insert into t values (4, 40, 2)")
    assert s.query("select g, tot from v3 order by g") == [(1, 30),
                                                          (2, 70)]


def test_view_replace_drop_errors(s):
    s.execute("create view w as select id from t")
    with pytest.raises(SQLError):
        s.execute("create view w as select v from t")
    s.execute("create or replace view w as select v from t")
    assert s.query("select count(*) from w") == [(3,)]
    s.execute("drop view w")
    with pytest.raises(SQLError):
        s.query("select * from w")
    s.execute("drop view if exists w")  # no error
    with pytest.raises(SQLError):
        s.execute("drop view w")


def test_view_name_collision_and_validation(s):
    with pytest.raises(SQLError):
        s.execute("create view t as select 1")  # table exists
    with pytest.raises(SQLError):
        s.execute("create view bad as select nosuch from t")
    with pytest.raises(SQLError):
        s.execute("create view bad (a, b) as select id from t")
