"""Subquery planning/execution edge cases (decorrelation, NULL semantics).

Counterpart of the reference's expression_rewriter + decorrelate rule tests
(reference: planner/core/expression_rewriter_test.go,
rule_decorrelate.go). Each case here pins a semantic corner that the
TPC-H corpus alone does not exercise.
"""

from __future__ import annotations

import pytest

from tidb_tpu.session import Session, SQLError


@pytest.fixture()
def s():
    s = Session()
    s.execute("create table t (id bigint, k bigint, a bigint)")
    s.execute("create table u (k bigint, b bigint)")
    s.execute("insert into t values (1, 10, 5), (2, 20, 50), (3, 30, 7)")
    s.execute("insert into u values (10, 1), (10, 2), (20, 100)")
    return s


def test_correlated_count_zero(s):
    # count(*) over an empty correlated group is 0, not a dropped row
    rows = s.query("select id from t where "
                   "(select count(*) from u where u.k = t.k) = 0 "
                   "order by id")
    assert rows == [(3,)]


def test_correlated_count_nonzero(s):
    rows = s.query("select id from t where "
                   "(select count(*) from u where u.k = t.k) = 2")
    assert rows == [(1,)]


def test_correlated_agg_inner(s):
    # classic Q17 shape: compare against a correlated average
    rows = s.query("select id from t where "
                   "a > (select avg(b) from u where u.k = t.k) "
                   "order by id")
    assert rows == [(1,)]  # id=1: 5 > avg(1,2)=1.5; id=2: 50 < 100 false


def test_select_star_no_hidden_columns(s):
    rows = s.query("select * from t where "
                   "a > (select avg(b) from u where u.k = t.k)")
    assert rows == [(1, 10, 5)]  # exactly t's columns, no #corr leakage


def test_not_in_empty_set_with_null_lhs(s):
    s.execute("insert into t values (4, null, 1)")
    # NOT IN over an empty set is TRUE for every row, even NULL lhs
    rows = s.query("select id from t where "
                   "k not in (select k from u where b > 1000) order by id")
    assert rows == [(1,), (2,), (3,), (4,)]


def test_not_in_with_null_in_subquery(s):
    s.execute("insert into u values (null, 9)")
    # any NULL in the subquery side empties NOT IN results
    rows = s.query("select id from t where k not in (select k from u)")
    assert rows == []


def test_not_in_null_lhs_filtered(s):
    s.execute("insert into t values (4, null, 1)")
    rows = s.query("select id from t where "
                   "k not in (select k from u) order by id")
    assert rows == [(3,)]  # k=30 unmatched; NULL k is UNKNOWN -> filtered


def test_scalar_subquery_in_order_by(s):
    rows = s.query("select id from t order by a - (select min(b) from u)")
    assert rows == [(1,), (3,), (2,)]


def test_scalar_subquery_in_agg_arg(s):
    rows = s.query("select sum(a - (select min(b) from u)) from t")
    assert rows == [(59,)]  # (5-1)+(50-1)+(7-1)


def test_exists_with_ungrouped_aggregate_is_true(s):
    # an ungrouped aggregate always yields exactly one row, so EXISTS
    # is constant TRUE regardless of the WHERE (MySQL semantics)
    rows = s.query("select count(*) from t where "
                   "exists (select max(b) from u where u.k = 99)")
    assert rows == [(3,)]
    with pytest.raises(SQLError):
        s.query("select id from t where exists "
                "(select k from u group by k having count(*) > 1)")


def test_exists_uncorrelated_true(s):
    rows = s.query("select count(*) from t where exists (select * from u)")
    assert rows == [(3,)]


def test_scalar_subquery_empty_is_null(s):
    rows = s.query("select id from t where "
                   "a > (select b from u where b > 1000)")
    assert rows == []


def test_scalar_subquery_multirow_errors(s):
    with pytest.raises(Exception):
        s.query("select id from t where a > (select b from u)")


def test_in_subquery_semi_dedup(s):
    # two matching u rows must not duplicate the t row (semi join)
    rows = s.query("select id from t where k in (select k from u) "
                   "order by id")
    assert rows == [(1,), (2,)]


def test_distributed_min_max():
    """min/max partials must merge with pmin/pmax, not psum (P2 over ICI)."""
    import jax

    from tidb_tpu.parallel import DistCopClient, make_mesh

    single = Session()
    single.execute(
        "create table m (g bigint not null, v bigint not null)")
    vals = [(i % 3, (i * 37) % 101 + 1) for i in range(512)]
    ins = ",".join(f"({g},{v})" for g, v in vals)
    single.execute(f"insert into m values {ins}")

    mesh = make_mesh(jax.devices()[:4])
    dist = Session(single.storage, cop=DistCopClient(mesh))
    sql = ("select g, min(v), max(v), sum(v), count(*) from m "
           "group by g order by g")
    assert dist.query(sql) == single.query(sql)


# ---- round-4 decorrelation extensions (reference: rule_decorrelate.go) ----

def test_correlated_in_subquery():
    from tidb_tpu.session import Session
    s = Session()
    s.execute("create table co (k bigint primary key, a bigint not null)")
    s.execute("create table ci (k bigint not null, b bigint not null)")
    s.execute("insert into co values (1, 10), (2, 20), (3, 30)")
    s.execute("insert into ci values (1, 10), (1, 11), (3, 99)")
    assert s.query("select k from co where a in "
                   "(select b from ci where ci.k = co.k) order by k") \
        == [(1,)]
    assert s.query("select k from co where a not in "
                   "(select b from ci where ci.k = co.k) order by k") \
        == [(2,), (3,)]
    # correlated IN with extra inner predicates
    assert s.query("select k from co where a in (select b from ci "
                   "where ci.k = co.k and ci.b > 10) order by k") == []


def test_exists_limit_and_trivial_aggregate():
    from tidb_tpu.session import Session
    s = Session()
    s.execute("create table eo (k bigint primary key)")
    s.execute("create table ei (k bigint)")
    s.execute("insert into eo values (1), (2)")
    s.execute("insert into ei values (1)")
    assert s.query("select k from eo where exists "
                   "(select 1 from ei where ei.k = eo.k limit 1) "
                   "order by k") == [(1,)]
    # ungrouped aggregate always yields one row: EXISTS is constant true
    assert s.query("select k from eo where exists "
                   "(select max(k) from ei where ei.k = eo.k) "
                   "order by k") == [(1,), (2,)]
    assert s.query("select k from eo where not exists "
                   "(select max(k) from ei where ei.k = eo.k)") == []
