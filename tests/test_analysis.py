"""Tests for the concurrency analysis plane (tidb_tpu/analysis/).

Static half: each rule fires on a minimal synthetic bad snippet and
stays silent on its good twin (SourceTree.from_files builds the
snippet trees); the engine's registry lint and baseline ratchet are
pinned; `python -m tidb_tpu.analysis --check` must exit 0 on the real
tree WITHOUT importing jax (the tier-1 wiring).

Dynamic half: the TIDB_TPU_LOCK_CHECK instrumented-lock wrapper — an
injected lock-order inversion produces the cycle finding (and
surfaces through the inspection plane as `lock-order-inversion`),
blocking syscalls under a hot lock are reported, zero overhead when
off is asserted structurally (plain threading primitives), and the
held-lock mirror backing the conftest leak guard empties on release.

Native half: a slow-marked torture test runs the PR 12 group-fsync
workload against the ASan/UBSan build of native/kvstore.cpp.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading

import pytest

from tidb_tpu.analysis import engine as eng
from tidb_tpu.analysis import lockcheck
from tidb_tpu.analysis import rules as _rules  # noqa: F401 — registers
from tidb_tpu.analysis.engine import SourceTree

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_rule(name: str, files: dict, aux=None):
    tree = SourceTree.from_files(files, aux)
    return [f for f in eng.run(tree, rules={name: eng.RULES[name]})
            if f.rule == name]


@pytest.fixture
def checker():
    """Armed lock checker with clean state; disarmed afterwards."""
    lockcheck.reset()
    lockcheck.enable()
    yield lockcheck
    lockcheck.disable()
    lockcheck.reset()


# ---- engine / registry ------------------------------------------------------

def test_rule_registry_lints_clean():
    assert len(eng.RULES) >= 8, sorted(eng.RULES)
    assert eng.lint_rules() == []


def test_rule_decorator_rejects_bad_metadata():
    with pytest.raises(ValueError):
        eng.rule("x", "warning", "")(lambda t: [])
    with pytest.raises(ValueError):
        eng.rule("x", "fatal", "ref")(lambda t: [])
    with pytest.raises(ValueError):
        eng.rule("bare-except", "warning", "ref")(lambda t: [])  # dup


def test_baseline_ratchet():
    """A finding not in the baseline fails check(); a baselined one
    passes; a baseline key that stopped firing reports stale."""
    bad = {"tidb_tpu/x.py": (
        "def f():\n"
        "    try:\n"
        "        pass\n"
        "    except:\n"
        "        pass\n")}
    tree = SourceTree.from_files(bad)
    rules = {"bare-except": eng.RULES["bare-except"]}
    findings = eng.run(tree, rules=rules)
    findings = [f for f in findings if f.rule == "bare-except"]
    assert len(findings) == 1
    key = findings[0].key()
    assert key == ("bare-except", "tidb_tpu/x.py", "f:0")

    new, _ = eng.check(tree, {})
    assert key in {f.key() for f in new}
    new2, stale2 = eng.check(tree, {key: "known"})
    assert key not in {f.key() for f in new2}
    dead = ("bare-except", "tidb_tpu/gone.py", "g:0")
    _, stale3 = eng.check(tree, {dead: "old"})
    assert dead in stale3


def test_cli_check_clean_and_jax_free():
    """The tier-1 wiring: `python -m tidb_tpu.analysis --check` exits
    0 on the REAL tree (every finding fixed or baselined) and the
    process never imports jax."""
    code = (
        "import sys\n"
        "from tidb_tpu.analysis.__main__ import main\n"
        "rc = main(['--check'])\n"
        "assert 'jax' not in sys.modules, 'analysis imported jax'\n"
        "sys.exit(rc)\n")
    env = dict(os.environ)
    env.pop("TIDB_TPU_LOCK_CHECK", None)
    env["PYTHONPATH"] = REPO
    r = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                       capture_output=True, text=True, timeout=300,
                       env=env)
    assert r.returncode == 0, r.stdout + r.stderr


# ---- static rules: fire on bad, silent on good ------------------------------

def test_blocking_call_under_hot_lock():
    bad = {"tidb_tpu/x.py": (
        "import os, threading\n"
        "class Storage:\n"
        "    def __init__(self):\n"
        "        self._commit_lock = threading.RLock()\n"
        "    def f(self):\n"
        "        with self._commit_lock:\n"
        "            os.fsync(3)\n")}
    out = run_rule("blocking-call-under-hot-lock", bad)
    assert len(out) == 1 and "os.fsync" in out[0].message

    # one level of same-class helper expansion (the closed_info shape)
    indirect = {"tidb_tpu/x.py": (
        "import os, threading\n"
        "class Storage:\n"
        "    def __init__(self):\n"
        "        self._commit_lock = threading.RLock()\n"
        "    def _wal_size(self):\n"
        "        return os.path.getsize('x')\n"
        "    def f(self):\n"
        "        with self._commit_lock:\n"
        "            return self._wal_size()\n")}
    out = run_rule("blocking-call-under-hot-lock", indirect)
    assert len(out) == 1 and "_wal_size" in out[0].message

    good = {"tidb_tpu/x.py": (
        "import os, threading\n"
        "class Storage:\n"
        "    def __init__(self):\n"
        "        self._commit_lock = threading.RLock()\n"
        "    def f(self):\n"
        "        with self._commit_lock:\n"
        "            x = 1\n"
        "        os.fsync(3)\n")}
    assert run_rule("blocking-call-under-hot-lock", good) == []

    # a cold lock of the same attr name on another class is NOT hot
    cold = {"tidb_tpu/x.py": (
        "import os, threading\n"
        "class Registry:\n"
        "    def __init__(self):\n"
        "        self._commit_lock = threading.Lock()\n")}
    assert run_rule("blocking-call-under-hot-lock", cold) == []


def test_lock_order_inversion_static():
    bad = {"tidb_tpu/x.py": (
        "import threading\n"
        "class A:\n"
        "    def __init__(self):\n"
        "        self._alock = threading.Lock()\n"
        "        self._block = threading.Lock()\n"
        "    def f(self):\n"
        "        with self._alock:\n"
        "            with self._block:\n"
        "                pass\n"
        "    def g(self):\n"
        "        with self._block:\n"
        "            with self._alock:\n"
        "                pass\n")}
    out = run_rule("lock-order", bad)
    assert len(out) == 1
    assert "A._alock" in out[0].item and "A._block" in out[0].item

    good = {"tidb_tpu/x.py": (
        "import threading\n"
        "class A:\n"
        "    def __init__(self):\n"
        "        self._alock = threading.Lock()\n"
        "        self._block = threading.Lock()\n"
        "    def f(self):\n"
        "        with self._alock:\n"
        "            with self._block:\n"
        "                pass\n"
        "    def g(self):\n"
        "        with self._alock:\n"
        "            with self._block:\n"
        "                pass\n")}
    assert run_rule("lock-order", good) == []

    # a nested def under the outer lock runs LATER, not under it
    deferred = {"tidb_tpu/x.py": (
        "import threading\n"
        "class A:\n"
        "    def __init__(self):\n"
        "        self._alock = threading.Lock()\n"
        "        self._block = threading.Lock()\n"
        "    def f(self):\n"
        "        with self._alock:\n"
        "            def cb():\n"
        "                with self._block:\n"
        "                    pass\n"
        "            return cb\n"
        "    def g(self):\n"
        "        with self._block:\n"
        "            with self._alock:\n"
        "                pass\n")}
    assert run_rule("lock-order", deferred) == []


def test_tls_frame_hygiene():
    bad = {"tidb_tpu/x.py": (
        "def f(rec, prev):\n"
        "    install_stage_recorder(rec)\n"
        "    other_work()\n"
        "    try:\n"
        "        pass\n"
        "    finally:\n"
        "        install_stage_recorder(prev)\n")}
    out = run_rule("tls-frame-hygiene", bad)
    assert len(out) == 1 and "install_stage_recorder" in out[0].item

    good_next = {"tidb_tpu/x.py": (
        "def f(rec, prev):\n"
        "    install_stage_recorder(rec)\n"
        "    try:\n"
        "        other_work()\n"
        "    finally:\n"
        "        install_stage_recorder(prev)\n")}
    assert run_rule("tls-frame-hygiene", good_next) == []

    good_inside = {"tidb_tpu/x.py": (
        "def f(rec, prev):\n"
        "    try:\n"
        "        install_stage_recorder(rec)\n"
        "        other_work()\n"
        "    finally:\n"
        "        install_stage_recorder(prev)\n")}
    assert run_rule("tls-frame-hygiene", good_inside) == []

    # context-manager-only frames must be `with` items
    bare_ctx = {"tidb_tpu/x.py": (
        "def f(cop, snap):\n"
        "    scope = cop.placement_scope(snap)\n"
        "    return scope\n")}
    out = run_rule("tls-frame-hygiene", bare_ctx)
    assert len(out) == 1 and "placement_scope" in out[0].item
    with_ctx = {"tidb_tpu/x.py": (
        "def f(cop, snap):\n"
        "    with cop.placement_scope(snap):\n"
        "        pass\n")}
    assert run_rule("tls-frame-hygiene", with_ctx) == []


def test_thread_discipline():
    bad = {"tidb_tpu/x.py": (
        "import threading\n"
        "def f():\n"
        "    t = threading.Thread(target=f, name='worker-1')\n"
        "    t.start()\n")}
    out = run_rule("thread-discipline", bad)
    assert any(i.item.endswith(":name") for i in out)
    assert any(i.item.endswith(":join") for i in out)  # non-daemon

    good = {"tidb_tpu/x.py": (
        "import threading\n"
        "def f():\n"
        "    t = threading.Thread(target=f, name='titpu-x',\n"
        "                         daemon=True)\n"
        "    t.start()\n")}
    assert run_rule("thread-discipline", good) == []

    # f-string name rooted in a titpu- _thread_prefix constant is fine
    prefixed = {"tidb_tpu/x.py": (
        "import threading\n"
        "class S:\n"
        "    _thread_prefix = 'titpu-rpc'\n"
        "    def f(self):\n"
        "        threading.Thread(target=self.f, daemon=True,\n"
        "            name=f'{self._thread_prefix}-conn').start()\n")}
    assert run_rule("thread-discipline", prefixed) == []


_FP_DECL = (
    "DECLARED = frozenset({\n"
    "    'kv/group-fsync',\n"
    "    'twopc/unused-point',\n"
    "})\n")


def test_failpoint_registry():
    files = {
        "tidb_tpu/util/failpoint.py": _FP_DECL,
        "tidb_tpu/a.py": (
            "from ..util import failpoint\n"
            "def f():\n"
            "    failpoint.inject('kv/group-fsync')\n"
            "    failpoint.inject('kv/undeclared')\n"),
    }
    # the ghost spec is assembled at runtime so THIS file never
    # contains it as a parseable literal (the rule scans tests/ for
    # env-var arming specs — including this very file)
    ghost = "daemon" + "/ghost"
    files["tests/test_x.py"] = (
        "from tidb_tpu.util.failpoint import failpoint\n"
        "def test_a():\n"
        "    with failpoint('rpc/not-a-point'):\n"
        "        pass\n"
        "env = {'TIDB_TPU_FAILPOINTS':\n"
        "       'kv/group-fsync=exit(1)@2;" + ghost + "=raise'}\n")
    out = run_rule("failpoint-registry", files)
    items = {f.item for f in out}
    assert items == {"kv/undeclared",      # inject of undeclared name
                     "twopc/unused-point",  # declared, no inject site
                     "rpc/not-a-point",     # test arms undeclared
                     ghost}                 # env spec arms undeclared

    clean = {
        "tidb_tpu/util/failpoint.py":
            "DECLARED = frozenset({'kv/group-fsync'})\n",
        "tidb_tpu/a.py": (
            "def f():\n"
            "    failpoint.inject('kv/group-fsync')\n"),
        "tests/test_x.py": (
            "ENV = {'TIDB_TPU_FAILPOINTS': 'kv/group-fsync=true'}\n"
            "PROSE = 'rc=137/rc=124 remain the last words'\n"),
    }
    assert run_rule("failpoint-registry", clean) == []


def test_bare_except():
    bad = {"tidb_tpu/x.py": (
        "def f():\n"
        "    try:\n"
        "        pass\n"
        "    except:\n"
        "        pass\n"
        "    try:\n"
        "        pass\n"
        "    except BaseException:\n"
        "        pass\n")}
    out = run_rule("bare-except", bad)
    assert len(out) == 2

    good = {"tidb_tpu/x.py": (
        "def f():\n"
        "    try:\n"
        "        pass\n"
        "    except Exception:\n"
        "        pass\n"
        "    try:\n"
        "        pass\n"
        "    except BaseException:\n"
        "        log()\n"
        "        raise\n")}
    assert run_rule("bare-except", good) == []


def test_engine_tag_enum():
    bad = {"tidb_tpu/x.py": (
        "def f(obs, r):\n"
        "    obs.note_engine('warp-drive')\n"
        "    r.engine = 'bogus(tag)'\n")}
    out = run_rule("engine-tag", bad)
    assert len(out) == 2

    good = {"tidb_tpu/x.py": (
        "def f(obs, r, n, mode):\n"
        "    obs.note_engine('device')\n"
        "    obs.note_engine(f'device[{mode}]@mesh{n}')\n"
        "    obs.note_engine('point')\n"
        "    r.engine = f'host(fragment:{mode})'\n"
        "    r.engine = 'ranged'\n"
        "    r.engine = f'replica@{mode}'\n"
        "    r.engine = computed()\n")}
    assert run_rule("engine-tag", good) == []


def test_metric_families():
    bad = {"tidb_tpu/x.py": (
        "def f(reg, ctx):\n"
        "    reg.counter('tidb_real_total', 'help')\n"
        "    ctx.metric_delta('tidb_ghost_total')\n")}
    out = run_rule("metric-families", bad)
    assert len(out) == 1 and out[0].item == "tidb_ghost_total"

    good = {"tidb_tpu/x.py": (
        "def f(reg, ctx):\n"
        "    reg.counter('tidb_real_total', 'help')\n"
        "    ctx.metric_delta('tidb_real_total')\n"
        "    ctx.metric('tidb_real_total{k=\"v\"}')\n")}
    assert run_rule("metric-families", good) == []


def test_config_knob_drift_synthetic():
    aux = {"config.toml.example": (
        "[storage]\n"
        "sync-log = \"commit\"\n"
        "bogus-knob = 1\n")}
    out = run_rule("config-knob-drift", {"tidb_tpu/x.py": ""}, aux)
    assert any(f.item == "storage.bogus-knob" and
               "no parsed Config field" in f.message for f in out)
    # absent aux (synthetic trees): the rule no-ops
    assert run_rule("config-knob-drift", {"tidb_tpu/x.py": ""}) == []


# ---- dynamic half: the instrumented lock wrapper ----------------------------

def test_zero_overhead_when_off():
    """Disabled, the factories hand back PLAIN threading primitives —
    not wrappers — so the production hot path pays literally nothing
    (the Top SQL contract)."""
    assert not lockcheck.enabled()
    lk = lockcheck.lock("test.off", hot=True)
    rl = lockcheck.rlock("test.off.r")
    assert isinstance(lk, type(threading.Lock()))
    assert isinstance(rl, type(threading.RLock()))
    # note_blocking is a single bool probe
    lockcheck.note_blocking("fsync", "noop")
    assert lockcheck.findings() == []


def test_injected_lock_order_inversion(checker):
    """The acceptance demo: two locks taken in opposite orders produce
    the cycle finding."""
    a = checker.lock("T.alpha")
    b = checker.lock("T.beta")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    cycles = checker.find_cycles()
    assert any(set(c) >= {"T.alpha", "T.beta"} for c in cycles), cycles
    kinds = {f["kind"] for f in checker.findings()}
    assert "lock-order-inversion" in kinds
    f = next(f for f in checker.findings()
             if f["kind"] == "lock-order-inversion")
    assert "T.alpha" in f["item"] and "T.beta" in f["item"]
    assert f["stack"]  # a sample stack rides along


def test_consistent_order_is_clean(checker):
    a = checker.lock("T.c1")
    b = checker.lock("T.c2")
    for _ in range(3):
        with a:
            with b:
                pass
    assert checker.find_cycles() == []
    assert checker.findings() == []


def test_blocking_under_hot_lock_dynamic(checker):
    hot = checker.lock("T.hot", hot=True)
    cold = checker.lock("T.cold")
    with cold:
        checker.note_blocking("fsync", "cold path")
    assert checker.findings() == []
    with hot:
        checker.note_blocking("fsync", "bad path")
    out = [f for f in checker.findings()
           if f["kind"] == "blocking-under-hot-lock"]
    assert len(out) == 1 and "T.hot" in out[0]["item"]


def test_rlock_reentrancy_and_cross_thread(checker):
    """Reentrant acquires don't self-edge; the inversion is detected
    across real threads (the deadlocked interleaving, run serially)."""
    a = checker.rlock("T.ra")
    b = checker.rlock("T.rb")

    def t1():
        with a:
            with a:        # reentrant: no a->a edge
                with b:
                    pass

    def t2():
        with b:
            with a:
                pass

    th1 = threading.Thread(target=t1, name="titpu-test-1")
    th1.start(); th1.join()
    th2 = threading.Thread(target=t2, name="titpu-test-2")
    th2.start(); th2.join()
    edges, _, _ = checker.GRAPH.snapshot()
    assert ("T.ra", "T.ra") not in edges
    assert any(set(c) >= {"T.ra", "T.rb"}
               for c in checker.find_cycles())


def test_held_snapshot_mirror(checker):
    """The conftest leak guard's probe: held while held, empty after
    release."""
    lk = checker.lock("T.held")
    lk.acquire()
    snap = checker.held_snapshot()
    assert any("T.held" in names for names in snap.values()), snap
    lk.release()
    assert checker.held_snapshot() == {}


def test_inspection_rule_surfaces_cycle(checker):
    """The PR 10 plane: an observed inversion shows up in
    inspection_result under rule lock-order-inversion."""
    from tidb_tpu import obs_inspect
    from tidb_tpu.store.storage import Storage

    a = checker.lock("T.ia")
    b = checker.lock("T.ib")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    st = Storage()
    try:
        rows = obs_inspect.result_rows(st)
        hits = [r for r in rows if r[0] == "lock-order-inversion"]
        assert hits, rows
        assert any("T.ia" in r[1] and "T.ib" in r[1] for r in hits)
        assert all(r[2] == "critical" for r in hits
                   if "->" in r[1])
        # the /debug/lockgraph payload carries the same cycle
        payload = checker.debug_payload()
        assert payload["enabled"] is True
        assert any("T.ia" in c for c in payload["cycles"])
    finally:
        st.close()


def test_instrumented_storage_runs_clean(checker, tmp_path):
    """A real durable storage under TIDB_TPU_LOCK_CHECK: product locks
    register, a write/commit workload leaves NO cycle and NO
    blocking-under-hot-lock finding (the PR 12 fsync fix, now pinned
    by instrumentation instead of code review)."""
    from tidb_tpu.session import Session
    from tidb_tpu.store.storage import Storage

    st = Storage(str(tmp_path / "store"), sync_log="commit")
    try:
        s = Session(st)
        s.execute("create table lc (a int primary key, b int)")
        for i in range(5):
            s.execute(f"insert into lc values ({i}, {i * 2})")
        assert s.query("select count(*) from lc") == [(5,)]
        _, _, locks = checker.GRAPH.snapshot()
        assert "Storage._commit_lock" in locks
        assert locks["Storage._commit_lock"] is True  # hot
        assert "SyncPolicy._lock" in locks
        bad = [f for f in checker.findings()]
        assert bad == [], bad
    finally:
        st.close()


# ---- native half: ASan/UBSan torture ---------------------------------------

_ASAN_CHILD = r"""
import sys, tempfile, threading
from tidb_tpu.kv import native
assert native._sanitize_requested()
kv = native.NativeOrderedKV(tempfile.mkdtemp(), sync_log="commit")
errors = []
def writer(i):
    try:
        for n in range(200):
            kv.put(0, b"k%d-%d" % (i, n), b"v" * 128)
            if n % 3 == 0:
                kv.delete(0, b"k%d-%d" % (i, n))
            kv.commit_sync()
    except Exception as e:
        errors.append(e)
def churner():
    try:
        for _ in range(20):
            kv.checkpoint()
            list(kv.scan(0, b"", b"\xff", limit=50))
    except Exception as e:
        errors.append(e)
threads = [threading.Thread(target=writer, args=(i,)) for i in range(4)]
threads.append(threading.Thread(target=churner))
for t in threads: t.start()
for t in threads: t.join()
assert not errors, errors
kv.close()
print("TORTURE_OK")
"""


@pytest.mark.slow
def test_native_sanitizer_group_fsync_torture():
    """TIDB_TPU_NATIVE_SANITIZE=1: rebuild native/kvstore.cpp under
    ASan/UBSan and run the PR 12 group-fsync workload (concurrent
    writers on commit_sync + checkpoint/scan churn) against it. Any
    use-after-free / data race the sanitizer can see fails the run."""
    gcc = subprocess.run(["gcc", "-print-file-name=libasan.so"],
                         capture_output=True, text=True)
    libasan = gcc.stdout.strip()
    if gcc.returncode != 0 or not os.path.isfile(libasan):
        pytest.skip("libasan not available")
    mk = subprocess.run(["make", "-C", os.path.join(REPO, "native"),
                         "sanitize"], capture_output=True, text=True,
                        timeout=180)
    assert mk.returncode == 0, mk.stderr
    env = dict(os.environ)
    env.update({
        "PYTHONPATH": REPO,
        "LD_PRELOAD": libasan,
        "TIDB_TPU_NATIVE_SANITIZE": "1",
        # the interpreter never frees everything at exit; leaks are
        # not what this test hunts
        "ASAN_OPTIONS": "detect_leaks=0:abort_on_error=1",
        "UBSAN_OPTIONS": "halt_on_error=1",
    })
    r = subprocess.run([sys.executable, "-c", _ASAN_CHILD],
                       capture_output=True, text=True, timeout=300,
                       env=env, cwd=REPO)
    out = r.stdout + r.stderr
    assert r.returncode == 0, out[-4000:]
    assert "TORTURE_OK" in r.stdout
    assert "AddressSanitizer" not in out
    assert "runtime error" not in out  # UBSan's report prefix
