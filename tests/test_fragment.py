"""Join fragments: device gather-join pushdown + host fallback.

Path-assertion tests (which engine ran the query) mirror the reference's
explaintest plan checks (cmd/explaintest/r/tpch.result pins cop/root task
splits); fallback tests pin the runtime gates (overlay rows, wide spans).
"""

import numpy as np
import pytest

import tidb_tpu.copr.fragment as F
from tidb_tpu.plan.fragment import PhysFragmentRead
from tidb_tpu.session import Session


@pytest.fixture
def star():
    """Fact table + two dimension tables (PK-keyed), snowflake chain:
    fact.cust -> customer.ck, customer.nk -> nation.nk."""
    s = Session()
    s.execute("""CREATE TABLE nation (
        nk INT NOT NULL PRIMARY KEY, nname VARCHAR(20))""")
    s.execute("""CREATE TABLE customer (
        ck INT NOT NULL PRIMARY KEY, nk INT, seg VARCHAR(10))""")
    s.execute("""CREATE TABLE fact (
        fid INT NOT NULL PRIMARY KEY, cust INT, amount DECIMAL(10,2),
        qty INT)""")
    s.execute("INSERT INTO nation VALUES (1,'de'),(2,'fr'),(3,'jp')")
    s.execute("""INSERT INTO customer VALUES
        (10,1,'auto'),(11,2,'auto'),(12,3,'steel'),(13,1,'steel')""")
    rng = np.random.default_rng(5)
    rows = []
    for i in range(400):
        cust = int(rng.choice([10, 11, 12, 13, 99]))  # 99 dangles
        rows.append(f"({i},{cust},{(i % 50) + 0.25},{i % 7})")
    s.execute("INSERT INTO fact VALUES " + ",".join(rows))
    _fold(s)
    return s


def _fold(s):
    """Fold committed deltas into column epochs (the steady state bulk
    loads land in; fresh INSERTs live in the MVCC overlay until then)."""
    safe = s.storage.safe_ts()
    for store in s.storage.tables.values():
        store.compact(safe)


JOIN_AGG = """
    SELECT nname, SUM(amount), COUNT(*)
    FROM fact, customer, nation
    WHERE fact.cust = customer.ck AND customer.nk = nation.nk
      AND seg = 'auto' AND qty < 5
    GROUP BY nname ORDER BY nname
"""

JOIN_ROWS = """
    SELECT fid, nname FROM fact, customer, nation
    WHERE fact.cust = customer.ck AND customer.nk = nation.nk
      AND qty = 3 AND seg = 'steel' ORDER BY fid
"""


def _plan_has_fragment(s, sql):
    from tidb_tpu.plan import PlanBuilder, optimize
    from tidb_tpu.sql.parser import parse_one

    plan = optimize(PlanBuilder(s.catalog, s.current_db).build_select(
        parse_one(sql)), s.storage.stats)

    def walk(p):
        if isinstance(p, PhysFragmentRead):
            return True
        return any(walk(c) for c in p.children)

    return walk(plan)


def _oracle(s, sql):
    """Same query with fragment recognition disabled (host join engine)."""
    import tidb_tpu.plan.fragment as PF
    orig = PF.apply_fragments
    PF.apply_fragments = lambda p: p
    try:
        return s.query(sql)
    finally:
        PF.apply_fragments = orig


def test_join_agg_planned_as_fragment(star):
    assert _plan_has_fragment(star, JOIN_AGG)


def test_join_agg_device_path(star, monkeypatch):
    """The snowflake aggregation must run on the device path — the host
    interpreter is a fallback, not the route (VERDICT: path assertions)."""
    def boom(frag, snaps):
        raise AssertionError("host fragment fallback taken")
    monkeypatch.setattr(F, "_host_fragment", boom)
    got = star.query(JOIN_AGG)
    assert [r[0] for r in got] == ["de", "fr"]  # jp customers are 'steel'
    want = _oracle(star, JOIN_AGG)
    assert got == want


def test_join_rows_device_path(star, monkeypatch):
    def boom(frag, snaps):
        raise AssertionError("host fragment fallback taken")
    monkeypatch.setattr(F, "_host_fragment", boom)
    got = star.query(JOIN_ROWS)
    assert got == _oracle(star, JOIN_ROWS)
    assert len(got) > 0


def test_dangling_keys_drop(star):
    """INNER semantics: fact rows pointing at absent customers vanish."""
    total = star.query("SELECT COUNT(*) FROM fact")[0][0]
    joined = star.query("""
        SELECT COUNT(*) FROM fact, customer
        WHERE fact.cust = customer.ck""")[0][0]
    dangling = star.query(
        "SELECT COUNT(*) FROM fact WHERE cust = 99")[0][0]
    assert joined == total - dangling


def test_null_join_keys_drop(star):
    star.execute("INSERT INTO fact VALUES (9001, NULL, 5.00, 3)")
    got = star.query("""
        SELECT COUNT(*) FROM fact, customer WHERE fact.cust = customer.ck
          AND fid = 9001""")
    assert got == [(0,)]


def test_overlay_build_rows_fall_back(star, monkeypatch):
    """Uncommitted rows on a build table force the host interpreter —
    results must stay correct either way."""
    called = {}
    orig = F._host_fragment

    def spy(frag, snaps):
        called["yes"] = True
        return orig(frag, snaps)
    monkeypatch.setattr(F, "_host_fragment", spy)
    star.execute("BEGIN")
    star.execute("INSERT INTO customer VALUES (14, 2, 'auto')")
    star.execute("INSERT INTO fact VALUES (9100, 14, 3.50, 1)")
    got = star.query(JOIN_AGG)
    star.execute("ROLLBACK")
    assert called.get("yes"), "expected host fallback for overlay build rows"
    # fr gains the new in-txn row's 3.50
    want = _oracle(star, JOIN_AGG)
    assert [r[0] for r in got] == [r[0] for r in want]


def test_committed_build_rows_visible(star):
    star.execute("INSERT INTO customer VALUES (15, 3, 'auto')")
    star.execute("INSERT INTO fact VALUES (9200, 15, 100.00, 1)")
    got = star.query(JOIN_AGG)
    assert "jp" in [r[0] for r in got]
    assert got == _oracle(star, JOIN_AGG)


def test_wide_key_span_falls_back(monkeypatch):
    s = Session()
    s.execute("CREATE TABLE dim (k BIGINT NOT NULL PRIMARY KEY, v INT)")
    s.execute("CREATE TABLE f (id INT NOT NULL PRIMARY KEY, k BIGINT)")
    s.execute("INSERT INTO dim VALUES (1, 10), (100000000, 20)")
    s.execute("INSERT INTO f VALUES (1, 1), (2, 100000000), (3, 5)")
    called = {}
    orig = F._host_fragment

    def spy(frag, snaps):
        called["yes"] = True
        return orig(frag, snaps)
    monkeypatch.setattr(F, "_host_fragment", spy)
    got = s.query("""
        SELECT SUM(v), COUNT(*) FROM f, dim WHERE f.k = dim.k
        GROUP BY v ORDER BY v""")
    assert called.get("yes"), "span gate should route to host"
    assert got == [(10, 1), (20, 1)]


def test_fragment_vs_host_differential(star):
    """Every supported shape agrees with the fragment-disabled engine."""
    queries = [
        JOIN_AGG,
        JOIN_ROWS,
        """SELECT nname, MIN(qty), MAX(qty), AVG(amount)
           FROM fact, customer, nation
           WHERE fact.cust = customer.ck AND customer.nk = nation.nk
           GROUP BY nname ORDER BY nname""",
        """SELECT COUNT(*) FROM fact, customer
           WHERE fact.cust = customer.ck AND amount > 20""",
    ]
    for q in queries:
        assert star.query(q) == _oracle(star, q), q


# ---------------- high-cardinality TopN aggregation ----------------

@pytest.fixture
def highcard():
    s = Session()
    s.execute("CREATE TABLE hc (k INT NOT NULL PRIMARY KEY, g INT, "
              "v DECIMAL(8,2))")
    rng = np.random.default_rng(11)
    rows = []
    for i in range(20000):
        rows.append(f"({i},{int(rng.integers(0, 18000))},"
                    f"{int(rng.integers(1, 500))}.25)")
    s.execute("INSERT INTO hc VALUES " + ",".join(rows))
    _fold(s)
    return s


HC_Q = ("SELECT g, SUM(v) AS sv, COUNT(*) FROM hc GROUP BY g "
        "ORDER BY sv DESC LIMIT 7")


def test_highcard_topn_device_path(highcard, monkeypatch):
    """GROUP BY over ~14k distinct keys (beyond the dense-segment cap)
    with an ORDER BY ... LIMIT consumer runs the sorted-run candidate
    kernel on device, digest-equal to the host engine."""
    def boom(frag, snaps):
        raise AssertionError("host fragment fallback taken")
    monkeypatch.setattr(F, "_host_fragment", boom)
    ran = {}
    orig = F._run_frag_batch

    def spy(cop, frag, snaps, prepared, spans, builds, overlay, mode=None):
        ran["mode"] = mode
        return orig(cop, frag, snaps, prepared, spans, builds, overlay,
                    mode=mode)
    monkeypatch.setattr(F, "_run_frag_batch", spy)
    got = highcard.query(HC_Q)
    assert ran.get("mode") == "hc", f"expected hc path, got {ran}"
    assert got == _oracle(highcard, HC_Q)
    assert len(got) == 7


def test_highcard_topn_join_device_path(star, monkeypatch):
    """Q3-shaped: join + high-cardinality group key + TopN; the dependent
    group keys (nation name via the join) ride along without sorting."""
    # widen fact ids into a high-card group key
    q = ("SELECT fid, nname, SUM(amount) AS sa FROM fact, customer, nation "
         "WHERE fact.cust = customer.ck AND customer.nk = nation.nk "
         "GROUP BY fid, nname ORDER BY sa DESC LIMIT 5")
    got = star.query(q)
    assert got == _oracle(star, q)
    assert len(got) == 5


def test_highcard_group_key_order(highcard):
    q = "SELECT g, SUM(v) FROM hc GROUP BY g ORDER BY g LIMIT 9"
    assert highcard.query(q) == _oracle(highcard, q)
