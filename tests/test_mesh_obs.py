"""Mesh flight recorder: per-shard skew, HBM provenance, compile-storm
telemetry (copr/mesh.py MeshFlightRecorder + the EXPLAIN ANALYZE /
infoschema / event surfaces).

Runs under the 8 virtual CPU devices the conftest forces. Pins the
ISSUE-8 acceptance criteria: EXPLAIN ANALYZE shows per-shard rows +
skew ratio on sharded scans AND joins, a skewed join raises the
warning + tidb_events entry, the HBM provenance ledger's live bytes
sum to the per-device buffer gauge, scrapes never initialize a backend
while the plane is inactive, and the single-device CopClient statement
path does zero recorder work.
"""

from __future__ import annotations

import re
import threading

import jax
import pytest

from tidb_tpu import obs
from tidb_tpu.bench.tpch import TPCH_Q6, load_lineitem
from tidb_tpu.copr import mesh as M
from tidb_tpu.copr.client import CopClient
from tidb_tpu.session import Session
from tidb_tpu.util import failpoint

N_ROWS = 20_000

JOIN_SQL = ("select dim.tag, sum(fact.v) from fact join dim "
            "on fact.k = dim.k group by dim.tag order by dim.tag")

MESH_CELL = re.compile(r"^shards=(\d+) skew=(\d+\.\d+) "
                       r"rows=\[(-?\d+(,-?\d+)*)?\]")


def make_plane(**kw):
    cfg = dict(enabled=True, shard_threshold_rows=512)
    cfg.update(kw)
    return M.MeshPlane(M.MeshConfig(**cfg))


def mesh_cells(session, sql):
    rows = session.execute("EXPLAIN ANALYZE " + sql).rows
    return [r[5] for r in rows if r[5]]


@pytest.fixture(scope="module")
def sessions():
    assert len(jax.devices()) >= 8, "conftest must provide 8 devices"
    single = Session(cop=CopClient())
    load_lineitem(single, N_ROWS)
    plane = make_plane()
    mesh = Session(single.storage, cop=plane.client_for(single.storage))
    return single, mesh, plane


@pytest.fixture(scope="module")
def join_corpus():
    """A fact/dim join big enough to shard the probe side."""
    single = Session(cop=CopClient())
    single.execute("create table dim (k int not null primary key, "
                   "tag varchar(8) not null)")
    single.execute("create table fact (id int not null primary key, "
                   "k int not null, v int not null)")
    single.execute("insert into dim values (1,'a'),(2,'b'),(3,'c')")
    vals = ",".join(f"({i},{i % 3 + 1},{i % 100})"
                    for i in range(1, 6001))
    single.execute(f"insert into fact values {vals}")
    single.storage.flush()
    plane = make_plane()
    mesh = Session(single.storage, cop=plane.client_for(single.storage))
    return single, mesh, plane


# ==================== EXPLAIN ANALYZE mesh column ====================

class TestExplainAnalyzeMeshColumn:
    def test_sharded_scan_shape(self, sessions):
        single, mesh, plane = sessions
        cells = mesh_cells(mesh, TPCH_Q6)
        assert cells, "sharded scan produced no mesh cell"
        m = MESH_CELL.match(cells[0])
        assert m, cells[0]
        assert int(m.group(1)) == 8
        rows = [int(x) for x in m.group(3).split(",")]
        assert len(rows) == 8
        # per-shard survivors sum to the filter's total matches
        want = single.query(
            "select count(*) from lineitem where "
            "l_shipdate >= date '1994-01-01' and "
            "l_shipdate < date '1994-01-01' + interval '1' year and "
            "l_discount between 0.05 and 0.07 and l_quantity < 24"
        )[0][0]
        assert sum(rows) == want, (rows, want)

    def test_sharded_join_shape(self, join_corpus):
        single, mesh, _ = join_corpus
        assert mesh.query(JOIN_SQL) == single.query(JOIN_SQL)
        cells = mesh_cells(mesh, JOIN_SQL)
        assert cells, "sharded join produced no mesh cell"
        m = MESH_CELL.match(cells[0])
        assert m, cells[0]
        assert int(m.group(1)) == 8
        assert len(m.group(3).split(",")) == 8

    def test_single_device_has_empty_mesh_cell(self, sessions):
        single, _, _ = sessions
        rs = single.execute("EXPLAIN ANALYZE " + TPCH_Q6)
        assert rs.column_names[5] == "mesh"
        assert rs.column_names[-1] == "wait_profile"
        assert all(not r[5] for r in rs.rows), rs.rows


# ==================== skew detector ====================

class TestSkewDetector:
    def test_failpoint_skew_raises_warning_and_event(self, join_corpus):
        single, mesh, _ = join_corpus
        base = obs.MESH_SKEW_WARNINGS.get()
        with failpoint.failpoint("mesh/skew", 64.0):
            mesh.query(JOIN_SQL)
        assert obs.MESH_SKEW_WARNINGS.get() > base
        warns = [w for w in mesh.warnings if "mesh skew" in w[2]]
        assert warns, mesh.warnings
        assert "skew-warn-ratio" in warns[0][2]
        evs = [e for e in single.storage.obs.events.snapshot()
               if e["kind"] == "mesh_skew"]
        assert evs and "64.00" in evs[-1]["detail"]
        # queryable through information_schema.tidb_events too
        rows = mesh.query("select kind, severity from "
                          "information_schema.tidb_events "
                          "where kind = 'mesh_skew'")
        assert rows and rows[0][1] == "warn"

    def test_hot_range_skews_naturally(self, sessions):
        """A predicate matching only the lowest orderkeys keeps every
        survivor on shard 0 of the row-sharded epoch: skew ~= 8 crosses
        the default warn ratio with NO failpoint."""
        single, mesh, plane = sessions
        mesh.query("select count(*), sum(l_quantity) from lineitem "
                   "where l_orderkey <= 500")
        warns = [w for w in mesh.warnings if "mesh skew" in w[2]]
        assert warns, mesh.warnings
        assert obs.MESH_SKEW_RATIO.get() >= plane.cfg.skew_warn_ratio

    def test_skew_rides_topsql_and_slow_log(self, join_corpus):
        single, mesh, _ = join_corpus
        st = single.storage
        st.obs.topsql.configure(enabled=True, window_s=3600)
        mesh.execute("set tidb_slow_log_threshold = 0")
        try:
            mesh.query(JOIN_SQL)
        finally:
            mesh.execute("set tidb_slow_log_threshold = 100000")
        # per-operator max-shard share in the Top SQL rows
        rows = mesh.query(
            "select operator, max_shard_share from "
            "information_schema.tidb_top_sql "
            "where digest_text like '%fact join dim%' "
            "and operator <> '(stmt)'")
        assert rows, "no operator rows in tidb_top_sql"
        assert any(r[1] and r[1] > 0 for r in rows), rows
        # and the slow log's mesh_skew column
        rows = mesh.query(
            "select mesh_skew from information_schema.slow_query "
            "where query like '%fact join dim%'")
        assert rows and any(r[0] >= 1.0 for r in rows), rows
        st.obs.topsql.configure(enabled=False)


# ==================== per-shard ring + HBM ledger ====================

class TestRecorderSurfaces:
    def test_tidb_mesh_shards_rows(self, sessions):
        _, mesh, _ = sessions
        mesh.query(TPCH_Q6)
        rows = mesh.query(
            "select digest, kind, operator, dispatches, shards, "
            "last_shard_rows, max_skew, in_rows, out_rows "
            "from information_schema.tidb_mesh_shards")
        assert rows, "dispatch ring empty"
        ent = next(r for r in rows if r[1] == "agg")
        assert len(ent[0]) == 16 and ent[4] == 8
        assert ent[3] >= 1 and ent[7] > 0
        assert len(ent[5].split(",")) == 8

    def test_ledger_sums_to_device_buffer_gauge(self, sessions):
        _, mesh, plane = sessions
        mesh.query(TPCH_Q6)
        # back-to-back reads share one memoized walk: the '(device)'
        # total rows must equal the per-device gauge source exactly
        totals = {r[0]: (r[5], r[6])
                  for r in M.storage_rows(mesh.storage)
                  if r[3] == "total"}
        per = plane.device_bytes()
        assert len(totals) == 8
        for dev, b in per.items():
            live, peak = totals[dev]
            assert live == b, (dev, live, b)
            assert peak >= live
        # the labeled gauge the probe publishes agrees
        obs.run_gauge_probes()  # process plane may differ; set directly
        for dev, b in per.items():
            obs.DEVICE_BUFFER_BYTES.set(b, device=dev)
            assert obs.DEVICE_BUFFER_BYTES.get(device=dev) == b

    def test_ledger_classifies_replicas(self, join_corpus):
        _, mesh, _ = join_corpus
        mesh.query(JOIN_SQL)
        kinds = {r[3] for r in M.storage_rows(mesh.storage)}
        assert "epoch" in kinds
        # the dim build broadcast-replicates and the perm table rides
        # along: provenance must name them
        assert "replica" in kinds or "perm" in kinds, kinds
        # table attribution resolves through the live epoch map
        names = {r[1] for r in M.storage_rows(mesh.storage)}
        assert "fact" in names, names

    def test_ring_is_bounded(self):
        single = Session(cop=CopClient())
        load_lineitem(single, 4096)
        plane = make_plane(shard_ring_cap=3)
        mesh = Session(single.storage,
                       cop=plane.client_for(single.storage))
        for q in range(6):
            mesh.query("select count(*), sum(l_quantity) from lineitem "
                       f"where l_orderkey > {q}")
        with mesh.cop.recorder._lock:
            assert len(mesh.cop.recorder._ring) <= 3

    def test_failed_statement_discards_pending_stats(self, sessions):
        """A statement that dies before the engine collects (interrupt,
        plan error) must not leak its queued per-shard stats into the
        next statement's mesh accounting."""
        import numpy as np
        _, mesh, _ = sessions
        rec = mesh.cop.recorder
        rec.note_pending("agg", "stalepending00ff",
                         np.asarray([[5, 5]] * 8, dtype=np.int32))
        with pytest.raises(Exception):
            mesh.execute("select no_such_col from lineitem")
        assert not getattr(rec._tls, "pending", None), \
            "failed statement left pending per-shard stats queued"
        mesh.query(TPCH_Q6)
        with rec._lock:
            assert "stalepending00ff" not in rec._ring

    def test_zero_match_bits_dispatch_keeps_shard_count(self):
        """A rows-mode dispatch whose filter matches zero rows is still
        an 8-way dispatch: shards must come from the observed arrays,
        not the (all-zero, hence absent) count basis."""
        import numpy as np
        import types
        plane = make_plane()
        rec = M.MeshFlightRecorder(plane)
        bits = types.SimpleNamespace(addressable_shards=[
            types.SimpleNamespace(device=types.SimpleNamespace(id=i),
                                  data=np.zeros(4, dtype=np.uint8))
            for i in range(8)])
        rec.note_pending("frag-rows", "zeromatchbits000", {"bits": bits})
        note = rec.collect()
        assert note is not None and note["shards"] == 8
        assert note["rows"] == [0] * 8
        with rec._lock:
            assert rec._ring["zeromatchbits000"]["shards"] == 8

    def test_bits_shard_counts_axis_ordered(self):
        """Per-shard popcounts list in device-id order, not device-name
        lexicographic order ('10' must not sort between '1' and '2')."""
        import numpy as np
        import types
        shards = [types.SimpleNamespace(
            device=types.SimpleNamespace(id=i),
            data=np.asarray([0xFF] * i, dtype=np.uint8))
            for i in range(12)]
        shards.reverse()  # arrival order must not matter either
        arr = types.SimpleNamespace(addressable_shards=shards)
        counts = M._bits_shard_counts(arr)
        assert counts.tolist() == [8 * i for i in range(12)]

    def test_partitioned_join_counts_routed_bytes(self, join_corpus):
        """A partitioned-build agg join exchanges probe rows inside
        the kernel: the reshard counter and the ring's routed_bytes
        must both see the routed payload."""
        single, _, _ = join_corpus
        plane = make_plane(replicate_threshold_bytes=1)
        part = Session(single.storage,
                       cop=plane.client_for(single.storage))
        base = obs.MESH_RESHARD_BYTES.get()
        assert part.query(JOIN_SQL) == single.query(JOIN_SQL)
        assert any("partb" in str(k) for k in part.cop._col_cache), \
            "partitioned build staging did not engage"
        assert obs.MESH_RESHARD_BYTES.get() > base, \
            "routed join did not count reshard bytes"
        with part.cop.recorder._lock:
            routed = [e for e in part.cop.recorder._ring.values()
                      if e["routed_bytes"] > 0]
        assert routed, "no ring entry recorded routed bytes"
        rows = part.query(
            "select routed_bytes from "
            "information_schema.tidb_mesh_shards "
            "where routed_bytes > 0")
        assert rows, "routed bytes missing from tidb_mesh_shards"

    def test_recorder_has_no_background_thread(self, sessions):
        _, mesh, _ = sessions
        mesh.query(TPCH_Q6)
        assert not [t for t in threading.enumerate()
                    if t.name.startswith("titpu-mesh")]

    def test_debug_payload_shape(self, sessions):
        import json
        _, mesh, _ = sessions
        mesh.query(TPCH_Q6)
        payload = M.debug_payload()
        assert set(payload) >= {"status", "dispatches", "compiles",
                                "storage"}
        json.dumps(payload)  # must stay JSON-serializable


# ==================== HBM watermark ====================

def test_hbm_watermark_event_edge_triggered():
    single = Session(cop=CopClient())
    load_lineitem(single, 4096)
    # a 1KiB "capacity" puts every device over the watermark
    plane = make_plane(hbm_bytes=1024, hbm_watermark_fraction=0.5)
    mesh = Session(single.storage, cop=plane.client_for(single.storage))
    mesh.query(TPCH_Q6)
    base = obs.MESH_HBM_WATERMARK.get(device="TFRT_CPU_0")
    plane.device_bytes()
    evs = [e for e in single.storage.obs.events.snapshot()
           if e["kind"] == "mesh_hbm_watermark"]
    assert evs, "no watermark event"
    assert obs.MESH_HBM_WATERMARK.get(device="TFRT_CPU_0") > base
    # edge-triggered: a second scrape above the line does not re-emit
    n = len(evs)
    plane.device_bytes()
    evs = [e for e in single.storage.obs.events.snapshot()
           if e["kind"] == "mesh_hbm_watermark"]
    assert len(evs) == n


# ==================== compile observability ====================

class TestCompileObservability:
    def test_compiles_counted_per_signature(self, sessions):
        _, mesh, _ = sessions
        mesh.query(TPCH_Q6)
        comps = mesh.cop.recorder.snapshot()["compiles"]
        assert comps, "no compiles observed"
        assert all(c["count"] >= 1 and c["total_s"] >= 0
                   for c in comps)
        assert obs.MESH_COMPILES.get(kind="agg") >= 1

    def test_recompile_storm_emits_event(self):
        plane = make_plane()
        rec = M.MeshFlightRecorder(plane)
        from tidb_tpu.store.storage import Storage
        st = Storage()
        rec.obs = st.obs
        base = obs.MESH_RECOMPILE_STORMS.get()
        for i in range(M.MeshFlightRecorder.STORM_COMPILES):
            rec.note_compile("agg", "sig-abc", 0.01,
                             full_key=("shard", "agg", "k", 256 << i))
        assert obs.MESH_RECOMPILE_STORMS.get() == base + 1
        evs = [e for e in st.obs.events.snapshot()
               if e["kind"] == "mesh_compile_storm"]
        assert evs and "sig-abc" in evs[0]["detail"]
        # further compiles of the same signature do not re-trip
        rec.note_compile("agg", "sig-abc", 0.01)
        assert obs.MESH_RECOMPILE_STORMS.get() == base + 1

    def test_compile_ring_bounded(self):
        plane = make_plane()
        rec = M.MeshFlightRecorder(plane)
        for i in range(M.MeshFlightRecorder.COMPILE_CAP + 32):
            rec.note_compile("agg", f"sig-{i}", 0.0)
        with rec._lock:
            assert len(rec._compiles) <= \
                M.MeshFlightRecorder.COMPILE_CAP


# ==================== scrape cost + inactive-plane hygiene ==========

class TestScrapeHygiene:
    def test_device_bytes_memoized_per_generation(self, sessions):
        _, mesh, plane = sessions
        mesh.query(TPCH_Q6)
        t1 = mesh.cop.telemetry()
        t2 = mesh.cop.telemetry()
        assert t1 is t2, "telemetry walk not memoized across scrapes"
        walks = []
        orig = M._walk_arrays

        def counting(o):
            walks.append(1)
            return orig(o)

        M._walk_arrays = counting
        try:
            plane.device_bytes()
            assert not walks, "memoized scrape still walked arrays"
            # a cache mutation invalidates the memo
            with mesh.cop._lock:
                mesh.cop._col_cache[("__probe__",)] = ()
            plane.device_bytes()
            assert walks, "cache mutation did not refresh telemetry"
        finally:
            M._walk_arrays = orig
            with mesh.cop._lock:
                del mesh.cop._col_cache[("__probe__",)]

    def test_inactive_scrape_never_inits_backend(self, monkeypatch):
        old = M.get_plane().cfg
        try:
            M.configure(enabled=False)

            def boom(*a, **k):
                raise AssertionError("scrape initialized a JAX backend")

            monkeypatch.setattr(jax, "devices", boom)
            monkeypatch.setattr(jax, "local_devices", boom,
                                raising=False)
            st = M.status()
            assert st["enabled"] is False
            obs.run_gauge_probes()
            M.debug_payload()
        finally:
            monkeypatch.undo()
            M.configure(enabled=old.enabled, axis_size=old.axis_size,
                        shard_threshold_rows=old.shard_threshold_rows,
                        replicate_threshold_bytes=(
                            old.replicate_threshold_bytes))


# ==================== zero-work on the plain client =================

def test_plain_client_statement_path_does_zero_recorder_work(
        monkeypatch):
    """With the mesh plane inactive the plain CopClient path must not
    touch the recorder at all: no pendings, no collections, no ring
    allocations — asserted by intercepting every recorder entry
    point."""
    calls: list[str] = []
    for meth in ("note_pending", "collect", "note_compile"):
        orig = getattr(M.MeshFlightRecorder, meth)

        def spy(self, *a, _m=meth, _o=orig, **k):
            calls.append(_m)
            return _o(self, *a, **k)

        monkeypatch.setattr(M.MeshFlightRecorder, meth, spy)
    s = Session(cop=CopClient())
    s.execute("create table z (a int primary key, b int)")
    s.execute("insert into z values (1,2),(2,3),(3,4)")
    s.query("select sum(b) from z where a >= 1")
    s.query("explain analyze select sum(b) from z where a >= 1")
    assert calls == [], calls
    # the base hooks are allocation-free constants
    assert s.cop.take_mesh_note() is None
    assert s.cop.drain_mesh_warnings() == ()


def test_cluster_mesh_tables_fan_out_local(sessions):
    """cluster_mesh_shards / cluster_mesh_storage materialize over the
    diag plane (single-member: the local short-circuit) with the
    instance column leading and error trailing."""
    _, mesh, _ = sessions
    mesh.query(TPCH_Q6)
    rows = mesh.query("select instance, digest, kind, error from "
                      "information_schema.cluster_mesh_shards")
    assert rows and all(r[0] == "local" and r[3] is None for r in rows)
    rows = mesh.query("select instance, device, kind, bytes, error "
                      "from information_schema.cluster_mesh_storage")
    assert rows and all(r[3] is None or r[3] >= 0 for r in rows)
