"""Top SQL attribution hygiene + server event log.

The resource-attribution plane (obs.TopSQL / obs.EventLog / the
per-operator StageRecorder split): digest-cap eviction into the
overflow bucket, window rotation, concurrent writers, exact zero
overhead when disabled, stage-sum/operator-wall agreement with the
statement wall time, event producer wiring, and the thread-hygiene
contract (the plane runs no background threads of its own).
"""

from __future__ import annotations

import threading
import time

import pytest

from tidb_tpu import obs
from tidb_tpu.obs import EventLog, TopSQL
from tidb_tpu.session import Session
from tidb_tpu.store.storage import Storage

from testkit import TestKit


# ---------------------------------------------------------------------------
# aggregator unit behavior
# ---------------------------------------------------------------------------

def test_digest_cap_evicts_into_overflow_bucket():
    t = TopSQL(enabled=True, window_s=60, digest_cap=2)
    for i in range(5):
        t.record(f"d{i}", f"select {i}", "test", 0.01, now=1000.0)
    buckets = t.snapshot()
    assert len(buckets) == 1
    b = buckets[0]
    assert set(b["digests"]) == {"d0", "d1"}
    assert b["other"] is not None
    assert b["other"]["exec_count"] == 3
    assert b["other"]["digest"] == TopSQL.OTHER
    # overflow keeps accumulating, never grows the map
    t.record("d9", "select 9", "test", 0.01, now=1001.0)
    assert t.snapshot()[0]["other"]["exec_count"] == 4


def test_window_rotation_bounded_ring():
    t = TopSQL(enabled=True, window_s=10, n_windows=3, digest_cap=8)
    for i in range(6):  # six distinct 10s windows -> ring keeps 3
        t.record("d", "select 1", "test", 0.01, now=1000.0 + i * 10)
    buckets = t.snapshot()
    assert len(buckets) == 3
    starts = [b["start"] for b in buckets]
    assert starts == sorted(starts)
    assert starts[-1] == 1050
    # same-window records aggregate instead of appending
    t.record("d", "select 1", "test", 0.02, now=1051.0)
    assert t.snapshot()[-1]["digests"]["d"]["exec_count"] == 2


def test_concurrent_writers_conserve_counts():
    t = TopSQL(enabled=True, window_s=3600, digest_cap=4)
    n_threads, per = 8, 200

    def work(k: int) -> None:
        for i in range(per):
            t.record(f"d{(k + i) % 6}", "q", "test", 0.001,
                     op_wall={"scan": 0.0005}, now=5000.0)

    threads = [threading.Thread(target=work, args=(k,))
               for k in range(n_threads)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    b = t.snapshot()[0]
    total = sum(e["exec_count"] for e in b["digests"].values())
    if b["other"] is not None:
        total += b["other"]["exec_count"]
    assert total == n_threads * per


def test_disabled_is_zero_allocation_and_zero_overhead():
    st = Storage()
    s = Session(st)
    s.execute("create table z (a int)")
    s.execute("insert into z values (1),(2)")
    topsql = st.obs.topsql
    assert not topsql.enabled

    # the session call site must not even CALL record (no digest hash,
    # no kwargs dict) while disabled
    calls = []
    topsql.record = lambda *a, **k: calls.append(1)  # type: ignore
    s.query("select a from z")
    assert calls == []
    # and a direct record on a disabled aggregator allocates nothing
    del topsql.record
    topsql.record("d", "q", "test", 0.1)
    assert topsql.snapshot() == []


def test_statement_feed_and_attribution_coverage():
    """A real join statement attributes the bulk of its wall time to
    named operators/stages, and the per-stage sums agree with the PR 2
    recorder (additive: never exceed the wall)."""
    st = Storage()
    st.obs.topsql.configure(enabled=True, window_s=3600)
    s = Session(st)
    s.execute("create table dim (k int primary key, tag varchar(8))")
    s.execute("create table fact (id int primary key, k int, v int)")
    s.execute("insert into dim values (1,'a'),(2,'b'),(3,'c')")
    s.execute("insert into fact values " + ",".join(
        f"({i},{i % 3 + 1},{i % 100})" for i in range(1, 4001)))
    sql = ("select dim.tag, sum(fact.v) from fact join dim "
           "on fact.k = dim.k group by dim.tag order by 2 desc limit 2")
    t0 = time.perf_counter()
    s.query(sql)
    wall = time.perf_counter() - t0

    # per-statement recorder view (what bench.py persists)
    assert s.last_op_wall, "operator wall attribution missing"
    ops = set(s.last_op_wall)
    assert any("join" in o or o == "fragment" for o in ops), ops
    attributed = sum(s.last_op_wall.values()) + sum(
        s.last_op_stages.get("(session)", {}).values())
    assert attributed <= wall * 1.05
    assert attributed >= wall * 0.5, (attributed, wall, s.last_op_wall)
    # stage sums are additive (exclusive accounting): <= wall
    assert sum(s.last_stages.values()) <= wall * 1.05

    # the continuous aggregator got the same breakdown
    buckets = st.obs.topsql.snapshot()
    assert buckets
    ent = next(e for b in buckets for e in b["digests"].values()
               if "join" in e["digest_text"])
    assert ent["exec_count"] >= 1
    assert ent["op_wall"], ent
    assert abs(sum(ent["op_wall"].values())
               - sum(s.last_op_wall.values())) < 1.0


def test_tidb_top_sql_memtable_and_status_view():
    st = Storage()
    st.obs.topsql.configure(enabled=True, window_s=3600)
    tk = TestKit(Session(st))
    tk.must_exec("create table m (a int primary key, b int)")
    tk.must_exec("insert into m values (1,10),(2,20),(3,30)")
    tk.must_query("select sum(b) from m where a >= 1")
    rows = tk.must_query(
        "select operator, op_time_ms, exec_count from "
        "information_schema.tidb_top_sql where digest_text like "
        "'select sum%'")
    assert rows, "tidb_top_sql empty"
    ops = {r[0] for r in rows}
    assert TopSQL.STMT in ops
    assert any(o not in (TopSQL.STMT,) for o in ops), ops
    stmt_row = next(r for r in rows if r[0] == TopSQL.STMT)
    assert stmt_row[2] >= 1
    # /status quick view
    top = st.obs.topsql.top_by_device(3)
    assert top and top[0]["exec_count"] >= 1


def test_cluster_top_sql_fans_out_local():
    st = Storage()
    st.obs.topsql.configure(enabled=True)
    tk = TestKit(Session(st))
    tk.must_exec("create table c1 (a int)")
    tk.must_exec("insert into c1 values (1)")
    tk.must_query("select a from c1")
    rows = tk.must_query(
        "select instance, operator from information_schema.cluster_top_sql")
    assert rows and all(r[0] == "local" for r in rows)


def test_cluster_top_sql_from_follower(tmp_path):
    """A follower's cluster_top_sql query fans out over the diag RPC
    plane and shows the LEADER's per-operator breakdown — the
    acceptance criterion's cross-server half."""
    from tidb_tpu.rpc.client import RpcOptions

    opts = RpcOptions(connect_timeout_ms=1000, request_timeout_ms=4000,
                      backoff_budget_ms=3000, lock_budget_ms=8000,
                      lease_ms=2000)
    leader = Storage(str(tmp_path / "leader"), shared=True,
                     rpc_listen="127.0.0.1:0", rpc_options=opts)
    follower = Storage(str(tmp_path / "follower"),
                       remote=f"127.0.0.1:{leader.rpc_server.port}",
                       rpc_options=opts)
    try:
        leader.obs.topsql.configure(enabled=True, window_s=3600)
        sl = Session(leader)
        sl.execute("create table ct (a int primary key, b int)")
        sl.execute("insert into ct values (1,1),(2,2),(3,3)")
        sl.query("select sum(b) from ct where a >= 1")
        sf = Session(follower)
        rows = sf.query(
            "select instance, digest_text, operator, op_time_ms from "
            "information_schema.cluster_top_sql")
        # the leader's breakdown is visible FROM the follower; the
        # follower itself (topsql disabled, no statements) rightly
        # contributes no rows — and no error row either
        assert all(r[0] != "local" for r in rows), rows
        lrows = [r for r in rows
                 if r[1] and "sum" in r[1] and "ct" in r[1]]
        assert lrows, rows
        ops = {r[2] for r in lrows}
        assert TopSQL.STMT in ops and len(ops) > 1, ops
    finally:
        follower.close()
        leader.close()


def test_no_threads_leaked_by_attribution_plane():
    before = {t.ident for t in threading.enumerate()}
    st = Storage()
    st.obs.topsql.configure(enabled=True)
    s = Session(st)
    s.execute("create table nt (a int)")
    s.execute("insert into nt values (1)")
    s.query("select a from nt")
    st.obs.events.record("governor_kill", detail="x")
    st.obs.topsql.snapshot()
    st.obs.events.snapshot()
    after = {t.ident for t in threading.enumerate()}
    assert after <= before, "attribution plane spawned threads"


# ---------------------------------------------------------------------------
# event log
# ---------------------------------------------------------------------------

def test_event_ring_bounded_and_ordered():
    ev = EventLog(cap=4)
    for i in range(10):
        ev.record("breaker_trip", detail=f"e{i}")
    snap = ev.snapshot()
    assert [e["detail"] for e in snap] == ["e6", "e7", "e8", "e9"]
    assert snap[0]["id"] < snap[-1]["id"]
    ev.configure(cap=2)
    assert len(ev.snapshot()) == 2


def test_governor_kill_event_attributed():
    st = Storage()
    st.governor.configure(limit_bytes=1, cooldown_ms=0)
    s = Session(st)
    s.execute("create table gk (a int)")
    s.execute("insert into gk values (1),(2),(3)")
    try:
        s.query("select a from gk order by a")
    except Exception:
        pass  # the kill may or may not land before completion
    kinds = [e["kind"] for e in st.obs.events.snapshot()]
    assert "governor_kill" in kinds
    ent = next(e for e in st.obs.events.snapshot()
               if e["kind"] == "governor_kill")
    assert "server-memory-limit" in ent["detail"]


def test_admission_shed_event_attributed():
    from tidb_tpu.util.governor import AdmissionTimeout

    st = Storage()
    st.admission.configure(tokens=1, timeout_ms=50)
    s1, s2 = Session(st), Session(st)
    s1.execute("create table sh (a int)")
    s1.execute("insert into sh values (1)")
    held = threading.Event()
    done = threading.Event()

    def hog() -> None:
        with st.admission.admit(0):
            held.set()
            done.wait(5.0)

    th = threading.Thread(target=hog)
    th.start()
    held.wait(5.0)
    try:
        with pytest.raises(AdmissionTimeout):
            s2.query("select a from sh")
    finally:
        done.set()
        th.join()
    ents = [e for e in st.obs.events.snapshot()
            if e["kind"] == "admission_shed"]
    assert ents and "select a from sh" in ents[0]["detail"]
    # shed outcome rides the Top SQL feed too when enabled
    rows = Session(st).query(
        "select kind from information_schema.tidb_events")
    assert ("admission_shed",) in rows


def test_fsync_stall_event(tmp_path):
    st = Storage(str(tmp_path / "d"), sync_log="commit")
    syncer = getattr(st.kv.kv, "_syncer", None)
    assert syncer is not None and syncer.on_stall is not None
    syncer.stall_ms = 0.0  # every fsync "stalls"
    s = Session(st)
    s.execute("create table fs (a int)")
    s.execute("insert into fs values (1)")
    st.close()
    kinds = [e["kind"] for e in st.obs.events.snapshot()]
    assert "fsync_stall" in kinds


def test_events_memtable_and_debug_routes():
    import json
    import urllib.request

    from tidb_tpu.server.server import Server

    storage = Storage()
    storage.obs.topsql.configure(enabled=True)
    srv = Server(storage, host="127.0.0.1", port=0, status_port=0)
    srv.start()
    try:
        s = Session(storage)
        s.execute("create table ev (a int)")
        s.execute("insert into ev values (1)")
        s.execute("select a from ev")
        storage.obs.events.record("checkpoint_stall", detail="t", conn_id=3)
        base = f"http://127.0.0.1:{srv.status_port}"
        with urllib.request.urlopen(base + "/debug/topsql",
                                    timeout=10) as resp:
            top = json.loads(resp.read())
        assert top["enabled"] and top["windows"]
        with urllib.request.urlopen(base + "/debug/events",
                                    timeout=10) as resp:
            evs = json.loads(resp.read())
        assert any(e["kind"] == "checkpoint_stall" for e in evs)
        with urllib.request.urlopen(base + "/status", timeout=10) as resp:
            status = json.loads(resp.read())
        assert status["top_sql"]["enabled"]
        assert status["top_sql"]["by_device_time"] is not None
    finally:
        srv.close()


def test_slow_log_carries_operator_breakdown():
    tk = TestKit()
    tk.must_exec("create table slw (a int primary key, b int)")
    tk.must_exec("insert into slw values (1,1),(2,2)")
    tk.must_exec("set tidb_slow_log_threshold = 0")
    tk.must_query("select sum(b) from slw")
    tk.must_exec("set tidb_slow_log_threshold = 100000")
    rows = tk.must_query(
        "select operators from information_schema.slow_query "
        "where query like '%sum(b) from slw%'")
    assert rows and any(r[0] for r in rows), rows
