"""Index subsystem: point get, index-ranged scans, unique enforcement.

Mirrors the reference's point-get / unique-index test surface
(executor/point_get_test.go, executor/batch_point_get_test.go,
executor/insert_test.go duplicate-key cases) in the testkit style.
"""

import pytest

from tidb_tpu.session import Session, SQLError


@pytest.fixture
def se():
    s = Session()
    s.execute(
        "CREATE TABLE t (id INT PRIMARY KEY, name VARCHAR(20), "
        "score INT, UNIQUE KEY uname (name))")
    s.execute(
        "INSERT INTO t VALUES (1,'a',10),(2,'b',20),(3,'c',30),(4,'d',40)")
    yield s
    s.rollback_if_active()


def explain(s, sql):
    return "\n".join(r[0] for r in s.query("EXPLAIN " + sql))


# ---------------- plans ----------------

def test_point_get_plan_pk(se):
    p = explain(se, "SELECT * FROM t WHERE id = 3")
    assert "PointGet" in p and "handles=[3]" in p


def test_batch_point_get_plan(se):
    p = explain(se, "SELECT * FROM t WHERE id IN (1, 3)")
    assert "PointGet" in p


def test_point_get_plan_unique_index(se):
    p = explain(se, "SELECT * FROM t WHERE name = 'b'")
    assert "PointGet" in p and "uname" in p


def test_full_scan_without_index(se):
    p = explain(se, "SELECT * FROM t WHERE score = 20")
    assert "PointGet" not in p and "TableRead" in p


# ---------------- execution ----------------

def test_point_get_pk(se):
    assert se.query("SELECT name FROM t WHERE id = 2") == [("b",)]
    assert se.query("SELECT name FROM t WHERE id = 99") == []


def test_batch_point_get(se):
    rows = se.query("SELECT id FROM t WHERE id IN (4, 1, 4) ORDER BY id")
    assert rows == [(1,), (4,)]


def test_point_get_unique_index(se):
    assert se.query("SELECT id, score FROM t WHERE name = 'c'") == [(3, 30)]
    assert se.query("SELECT id FROM t WHERE name = 'zz'") == []


def test_point_get_residual_filter(se):
    assert se.query("SELECT id FROM t WHERE id = 2 AND score > 25") == []
    assert se.query("SELECT id FROM t WHERE id = 3 AND score > 25") == [(3,)]


def test_point_get_sees_txn_buffer(se):
    se.execute("BEGIN")
    se.execute("INSERT INTO t VALUES (10,'x',100)")
    assert se.query("SELECT name FROM t WHERE id = 10") == [("x",)]
    se.execute("DELETE FROM t WHERE id = 1")
    assert se.query("SELECT * FROM t WHERE id = 1") == []
    se.execute("ROLLBACK")
    assert se.query("SELECT COUNT(*) FROM t WHERE id = 1") == [(1,)]


def test_point_get_after_update(se):
    se.execute("UPDATE t SET score = 99 WHERE id = 2")
    assert se.query("SELECT score FROM t WHERE id = 2") == [(99,)]
    assert se.query("SELECT score FROM t WHERE name = 'b'") == [(99,)]


# ---------------- secondary (non-unique) index ranged scan ----------------

def test_index_ranged_scan():
    s = Session()
    s.execute("CREATE TABLE r (id INT PRIMARY KEY, grp VARCHAR(5), v INT, "
              "KEY kgrp (grp))")
    s.execute("INSERT INTO r VALUES (1,'a',1),(2,'b',2),(3,'a',3),"
              "(4,'c',4),(5,'a',5)")
    p = explain(s, "SELECT v FROM r WHERE grp = 'a'")
    assert "index:kgrp" in p
    assert s.query("SELECT v FROM r WHERE grp = 'a' ORDER BY v") == \
        [(1,), (3,), (5,)]
    assert s.query(
        "SELECT COUNT(*), SUM(v) FROM r WHERE grp = 'a'") == [(3, 9)]
    # index scan + residual filter
    assert s.query("SELECT v FROM r WHERE grp = 'a' AND v > 2 ORDER BY v") \
        == [(3,), (5,)]
    # absent dictionary string: provably empty
    assert s.query("SELECT v FROM r WHERE grp = 'zz'") == []


def test_index_scan_sees_deltas():
    s = Session()
    s.execute("CREATE TABLE r (id INT PRIMARY KEY, grp VARCHAR(5), "
              "KEY kgrp (grp))")
    s.execute("INSERT INTO r VALUES (1,'a'),(2,'b')")
    s.execute("INSERT INTO r VALUES (3,'a')")
    s.execute("UPDATE r SET grp = 'a' WHERE id = 2")
    assert s.query("SELECT COUNT(*) FROM r WHERE grp = 'a'") == [(3,)]
    s.execute("DELETE FROM r WHERE id = 1")
    assert s.query("SELECT COUNT(*) FROM r WHERE grp = 'a'") == [(2,)]


# ---------------- unique enforcement ----------------

def test_insert_duplicate_pk(se):
    with pytest.raises(SQLError, match="Duplicate entry '2' for key 'PRIMARY'"):
        se.execute("INSERT INTO t VALUES (2,'zz',0)")


def test_insert_duplicate_unique(se):
    with pytest.raises(SQLError, match="for key 'uname'"):
        se.execute("INSERT INTO t VALUES (9,'a',0)")


def test_insert_duplicate_within_statement(se):
    with pytest.raises(SQLError, match="Duplicate"):
        se.execute("INSERT INTO t VALUES (7,'p',0),(8,'p',0)")


def test_unique_allows_multiple_nulls(se):
    se.execute("INSERT INTO t (id, name, score) VALUES (7,NULL,0),(8,NULL,0)")
    assert se.query("SELECT COUNT(*) FROM t WHERE name IS NULL") == [(2,)]


def test_replace_semantics(se):
    # replace by pk: old row vanishes, affected counts 2 (MySQL)
    r = se.execute("REPLACE INTO t VALUES (2,'bb',21)")
    assert r.affected == 2
    assert se.query("SELECT name, score FROM t WHERE id = 2") == [("bb", 21)]
    # replace by unique key: displaces the row with name 'a' (id 1)
    se.execute("REPLACE INTO t VALUES (11,'a',12)")
    assert se.query("SELECT id FROM t WHERE name = 'a'") == [(11,)]
    assert se.query("SELECT * FROM t WHERE id = 1") == []
    # replace with no conflict behaves as plain insert
    r = se.execute("REPLACE INTO t VALUES (20,'t20',0)")
    assert r.affected == 1


def test_update_duplicate_pk(se):
    with pytest.raises(SQLError, match="PRIMARY"):
        se.execute("UPDATE t SET id = 1 WHERE id = 2")


def test_update_duplicate_unique(se):
    with pytest.raises(SQLError, match="uname"):
        se.execute("UPDATE t SET name = 'a' WHERE id = 2")


def test_update_pk_move(se):
    se.execute("UPDATE t SET id = 50 WHERE id = 2")
    assert se.query("SELECT * FROM t WHERE id = 2") == []
    assert se.query("SELECT name FROM t WHERE id = 50") == [("b",)]


def test_update_unique_to_self_ok(se):
    se.execute("UPDATE t SET name = 'b' WHERE id = 2")
    assert se.query("SELECT name FROM t WHERE id = 2") == [("b",)]


def test_string_primary_key():
    s = Session()
    s.execute("CREATE TABLE sp (code VARCHAR(8) PRIMARY KEY, v INT)")
    s.execute("INSERT INTO sp VALUES ('x',1),('y',2)")
    with pytest.raises(SQLError, match="Duplicate"):
        s.execute("INSERT INTO sp VALUES ('x',3)")
    assert s.query("SELECT v FROM sp WHERE code = 'y'") == [(2,)]
    p = "\n".join(r[0] for r in s.query(
        "EXPLAIN SELECT v FROM sp WHERE code = 'y'"))
    assert "PointGet" in p


def test_column_level_unique():
    s = Session()
    s.execute("CREATE TABLE cu (id INT PRIMARY KEY, email VARCHAR(30) UNIQUE)")
    s.execute("INSERT INTO cu VALUES (1,'a@x'),(2,'b@x')")
    with pytest.raises(SQLError, match="Duplicate"):
        s.execute("INSERT INTO cu VALUES (3,'a@x')")


def test_update_unique_vacated_value():
    # multi-row UPDATE where a later row takes a value an earlier row
    # vacated must not raise a spurious duplicate (code-review regression)
    s = Session()
    s.execute("CREATE TABLE vv (id INT PRIMARY KEY, u INT UNIQUE)")
    s.execute("INSERT INTO vv VALUES (1,10),(2,20)")
    s.execute("UPDATE vv SET u = u - 10")
    assert s.query("SELECT u FROM vv ORDER BY u") == [(0,), (10,)]


def test_index_lookup_on_snapshot_older_than_live_epoch():
    # a snapshot pinned before a compaction must search with ITS epoch's
    # permutation, not the live store's (code-review regression)
    s = Session()
    s.execute("CREATE TABLE ep (id INT PRIMARY KEY, k VARCHAR(4), "
              "KEY kk (k))")
    s.execute("INSERT INTO ep VALUES (1,'a'),(2,'b'),(3,'a'),(4,'c')")
    s.execute("BEGIN")
    assert s.query("SELECT COUNT(*) FROM ep WHERE k = 'a'") == [(2,)]
    # concurrent writer folds a bigger epoch while our txn snapshot is live
    s2 = Session(s.storage)
    s2.execute("INSERT INTO ep VALUES (5,'a'),(6,'a'),(7,'a'),(8,'a')")
    s.storage.flush()
    assert s.query("SELECT COUNT(*) FROM ep WHERE k = 'a'") == [(2,)]
    s.execute("COMMIT")
    assert s.query("SELECT COUNT(*) FROM ep WHERE k = 'a'") == [(6,)]


def test_contradictory_eq_with_subquery():
    # contradiction path must not push a scalar subquery into the ranged
    # DAG (code-review regression)
    s = Session()
    s.execute("CREATE TABLE ct (id INT PRIMARY KEY, u INT UNIQUE, v INT)")
    s.execute("CREATE TABLE o (x INT)")
    s.execute("INSERT INTO ct VALUES (1,1,1)")
    s.execute("INSERT INTO o VALUES (1)")
    assert s.query("SELECT * FROM ct WHERE u = 1 AND u = 2 "
                   "AND id = (SELECT x FROM o)") == []


# ---------------- larger table: index correctness vs scan oracle --------

def test_index_vs_scan_oracle():
    import numpy as np

    rng = np.random.default_rng(7)
    s = Session()
    s.execute("CREATE TABLE big (id INT PRIMARY KEY, k INT, v INT, "
              "KEY kk (k))")
    rows = ", ".join(
        f"({i}, {int(rng.integers(0, 50))}, {int(rng.integers(0, 1000))})"
        for i in range(500))
    s.execute(f"INSERT INTO big VALUES {rows}")
    # compaction fold then more deltas on top
    s.storage.flush()
    s.execute("INSERT INTO big VALUES (1000, 7, 1), (1001, 7, 2)")
    s.execute("DELETE FROM big WHERE id < 20")
    want = s.query("SELECT SUM(v), COUNT(*) FROM big WHERE k + 0 = 7")
    got = s.query("SELECT SUM(v), COUNT(*) FROM big WHERE k = 7")
    assert got == want


# ---------------- index merge (reference: index_merge_reader.go) --------


@pytest.fixture
def merge_se():
    s = Session()
    s.execute(
        "CREATE TABLE im (id INT PRIMARY KEY, a INT, b INT, c INT, "
        "KEY ka (a), KEY kb (b))")
    s.execute(
        "INSERT INTO im VALUES (1,1,10,100),(2,2,20,200),(3,3,30,300),"
        "(4,1,40,400),(5,5,10,500),(6,6,60,600)")
    yield s
    s.rollback_if_active()


def test_index_merge_plan_shape(merge_se):
    plan = explain(merge_se, "select * from im where a = 1 or b = 10")
    assert "IndexMerge(union)" in plan, plan
    assert "ka" in plan and "kb" in plan


def test_index_merge_union_correctness(merge_se):
    rows = merge_se.query(
        "select id from im where a = 1 or b = 10 order by id")
    assert [r[0] for r in rows] == [1, 4, 5]
    # three-way OR incl. the pk-handle column
    rows = merge_se.query(
        "select id from im where a = 1 or b = 60 or id = 2 order by id")
    assert [r[0] for r in rows] == [1, 2, 4, 6]


def test_index_merge_residual_conjunct(merge_se):
    # extra AND conjunct not covered by either index is re-checked
    rows = merge_se.query(
        "select id from im where (a = 1 or b = 10) and c >= 400 "
        "order by id")
    assert [r[0] for r in rows] == [4, 5]


def test_index_merge_disjunct_conjunction(merge_se):
    # a disjunct that is itself a conjunction: branch over-approximates,
    # residual filter restores exactness
    rows = merge_se.query(
        "select id from im where (a = 1 and c = 100) or b = 60 "
        "order by id")
    assert [r[0] for r in rows] == [1, 6]


def test_index_merge_no_path_without_full_cover(merge_se):
    # c has no index: one disjunct unservable -> no IndexMerge
    plan = explain(merge_se, "select * from im where a = 1 or c = 100")
    assert "IndexMerge" not in plan


def test_index_merge_sees_txn_buffer(merge_se):
    s = merge_se
    s.execute("begin")
    s.execute("insert into im values (7,1,70,700)")
    s.execute("update im set b = 10 where id = 2")
    s.execute("delete from im where id = 5")
    rows = s.query("select id from im where a = 1 or b = 10 order by id")
    assert [r[0] for r in rows] == [1, 2, 4, 7]
    s.execute("rollback")
    rows = s.query("select id from im where a = 1 or b = 10 order by id")
    assert [r[0] for r in rows] == [1, 4, 5]
