"""Roles: CREATE/DROP ROLE, GRANT role TO user, SET [DEFAULT] ROLE,
privilege flow through active roles (reference: privilege/privileges
role graph, executor/set_role, MySQL 8 semantics)."""

import pytest

from testkit import TestKit
from tidb_tpu.session import Session


@pytest.fixture()
def tk():
    t = TestKit()
    t.must_exec("create table rt (a int)")
    t.must_exec("insert into rt values (1), (2)")
    return t


def _user_session(tk, name):
    s = Session(tk.session.storage)
    s.execute("use test")
    s.user = name
    return s


def test_role_grants_flow_through_activation(tk):
    tk.must_exec("create role 'reader'")
    tk.must_exec("grant select on test.* to 'reader'")
    tk.must_exec("create user 'u1' identified by ''")
    tk.must_exec("grant 'reader' to 'u1'")
    u = _user_session(tk, "u1")
    # granted but NOT active: access denied
    with pytest.raises(Exception):
        u.execute("select a from rt")
    u.execute("set role 'reader'")
    assert u.execute("select a from rt order by a").rows == [(1,), (2,)]
    u.execute("set role none")
    with pytest.raises(Exception):
        u.execute("select a from rt")
    u.execute("set role all")
    assert len(u.execute("select a from rt").rows) == 2


def test_set_role_requires_granted(tk):
    tk.must_exec("create role 'r2'")
    tk.must_exec("create user 'u2' identified by ''")
    u = _user_session(tk, "u2")
    with pytest.raises(Exception):
        u.execute("set role 'r2'")


def test_default_roles_and_login_activation(tk):
    tk.must_exec("create role 'writer'")
    tk.must_exec("grant select, insert on test.* to 'writer'")
    tk.must_exec("create user 'u3' identified by ''")
    tk.must_exec("grant 'writer' to 'u3'")
    tk.must_exec("set default role all to 'u3'")
    pm = tk.session.storage.privileges
    assert pm.default_roles("u3") == {"writer"}
    # set role default picks them up
    u = _user_session(tk, "u3")
    u.execute("set role default")
    u.execute("insert into rt values (3)")
    assert len(u.execute("select a from rt").rows) == 3


def test_nested_roles_expand_transitively(tk):
    tk.must_exec("create role 'base', 'derived'")
    tk.must_exec("grant select on test.* to 'base'")
    tk.must_exec("grant 'base' to 'derived'")
    tk.must_exec("create user 'u4' identified by ''")
    tk.must_exec("grant 'derived' to 'u4'")
    u = _user_session(tk, "u4")
    u.execute("set role 'derived'")
    assert len(u.execute("select a from rt").rows) == 2


def test_drop_role_removes_edges_and_access(tk):
    tk.must_exec("create role 'temp'")
    tk.must_exec("grant select on test.* to 'temp'")
    tk.must_exec("create user 'u5' identified by ''")
    tk.must_exec("grant 'temp' to 'u5'")
    u = _user_session(tk, "u5")
    u.execute("set role 'temp'")
    assert len(u.execute("select a from rt").rows) == 2
    tk.must_exec("drop role 'temp'")
    # the active role's account is gone: grants no longer resolve
    with pytest.raises(Exception):
        u.execute("select a from rt")


def test_revoke_role(tk):
    tk.must_exec("create role 'rr'")
    tk.must_exec("grant select on test.* to 'rr'")
    tk.must_exec("create user 'u6' identified by ''")
    tk.must_exec("grant 'rr' to 'u6'")
    tk.must_exec("revoke 'rr' from 'u6'")
    u = _user_session(tk, "u6")
    with pytest.raises(Exception):
        u.execute("set role 'rr'")


def test_show_grants_lists_roles(tk):
    tk.must_exec("create role 'viewer'")
    tk.must_exec("create user 'u7' identified by ''")
    tk.must_exec("grant 'viewer' to 'u7'")
    rows = tk.must_query("show grants for 'u7'")
    assert any("'viewer'" in r[0] for r in rows)


def test_roles_cannot_login(tk):
    tk.must_exec("create role 'nologin'")
    pm = tk.session.storage.privileges
    assert not pm.verify_native("nologin", b"x" * 20, b"")


def test_show_grants_output_parses_back(tk):
    """The 'role'@'host' form SHOW GRANTS emits must round-trip."""
    tk.must_exec("create role 'rt1'")
    tk.must_exec("create user 'u9' identified by ''")
    tk.must_exec("grant 'rt1'@'%' to 'u9'@'%'")
    assert tk.session.storage.privileges.roles_of("u9") == {"rt1"}


def test_partial_failure_mutates_nothing(tk):
    pm = tk.session.storage.privileges
    tk.must_exec("create role 'ok1'")
    with pytest.raises(Exception):
        tk.must_exec("create role 'fresh', 'ok1'")  # ok1 exists
    assert not pm.is_role("fresh")
    with pytest.raises(Exception):
        tk.must_exec("grant 'ok1' to 'ghost_user'")
    tk.must_exec("create user 'u10' identified by ''")
    with pytest.raises(Exception):
        # second target unknown: first must stay unmodified
        tk.session.execute("grant 'ok1' to 'u10', 'ghost_user'")
    assert pm.roles_of("u10") == set()


def test_set_default_role_multi_user_atomic(tk):
    pm = tk.session.storage.privileges
    tk.must_exec("create role 'dr'")
    tk.must_exec("create user 'u12' identified by ''")
    tk.must_exec("grant 'dr' to 'u12'")
    with pytest.raises(Exception):
        tk.must_exec("set default role all to 'u12', 'ghost'")
    assert pm.default_roles("u12") == set()


def test_trace_dml_shows_twopc_spans(tk):
    rows = tk.must_query("trace insert into rt values (42)")
    ops = [r[0] for r in rows]
    assert any("twopc.prewrite" in o for o in ops), ops
    assert any("twopc.commit" in o for o in ops), ops


def test_drop_user_clears_role_edges(tk):
    pm = tk.session.storage.privileges
    tk.must_exec("create role 'edge'")
    tk.must_exec("create user 'u11' identified by ''")
    tk.must_exec("grant 'edge' to 'u11'")
    tk.must_exec("drop user 'edge'")  # DROP USER drops roles too
    assert pm.roles_of("u11") == set()
    tk.must_exec("create role 'edge'")  # re-created: NOT re-granted
    assert pm.roles_of("u11") == set()


def test_roles_survive_restart(tmp_path):
    from tidb_tpu.store.storage import Storage
    st = Storage(str(tmp_path))
    s = Session(st)
    s.execute("create role 'persisted'")
    s.execute("grant select on *.* to 'persisted'")
    s.execute("create user 'u8' identified by ''")
    s.execute("grant 'persisted' to 'u8'")
    st.close()
    st2 = Storage(str(tmp_path))
    pm = st2.privileges
    assert pm.is_role("persisted")
    assert pm.roles_of("u8") == {"persisted"}
    assert pm.check("u8", "SELECT", "any", "t", roles={"persisted"})
    st2.close()
