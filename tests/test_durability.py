"""Durability + restart recovery: nothing committed may die with the process.

The durable Storage(path) keeps three planes (reference analogs cited in
store/storage.py): the KV WAL+snapshot (unistore/badger persistence,
go.mod:34), columnar epoch snapshots (the TiFlash-style fold checkpoint),
and catalog/stats/DDL state in the meta keyspace (meta/meta.go:59).
Reopening the directory must recover schema, rows, auto-increment,
pending DDL, and resolve orphaned percolator locks — the bootstrap-from-KV
path of session/session.go:2090.

"Crash" here = dropping the Storage without close(): the WAL is appended
synchronously, so an unclean exit loses nothing committed.
"""

import numpy as np
import pytest

from tidb_tpu.session import Session
from tidb_tpu.store.storage import Storage


def crash(storage):
    """Simulate process death: release file handles WITHOUT checkpointing."""
    close = getattr(storage.kv.kv, "close", None)
    if close:
        close()


def test_rows_schema_survive_crash(tmp_path):
    p = str(tmp_path / "db")
    st = Storage(p)
    s = Session(st)
    s.execute("CREATE TABLE t (id INT PRIMARY KEY, v INT, name VARCHAR(20))")
    s.execute("INSERT INTO t VALUES (1, 10, 'alpha'), (2, 20, 'beta')")
    s.execute("UPDATE t SET v = 25 WHERE id = 2")
    s.execute("INSERT INTO t VALUES (3, 30, NULL)")
    s.execute("DELETE FROM t WHERE id = 1")
    crash(st)

    st2 = Storage(p)
    s2 = Session(st2)
    assert s2.query("SELECT id, v, name FROM t ORDER BY id") == [
        (2, 25, "beta"), (3, 30, None)]
    # schema intact: unknown column still errors, insert works
    s2.execute("INSERT INTO t VALUES (4, 40, 'gamma')")
    assert s2.query("SELECT COUNT(*) FROM t")[0][0] == 3


def test_duplicate_key_still_enforced_after_reopen(tmp_path):
    p = str(tmp_path / "db")
    st = Storage(p)
    s = Session(st)
    s.execute("CREATE TABLE u (id INT PRIMARY KEY, email VARCHAR(40) UNIQUE)")
    s.execute("INSERT INTO u VALUES (1, 'a@x.com')")
    crash(st)

    s2 = Session(Storage(p))
    with pytest.raises(Exception, match="Duplicate"):
        s2.execute("INSERT INTO u VALUES (2, 'a@x.com')")
    with pytest.raises(Exception, match="Duplicate"):
        s2.execute("INSERT INTO u VALUES (1, 'b@x.com')")


def test_bulk_load_and_compaction_epochs_survive(tmp_path):
    from tidb_tpu.bench.tpch import TPCH_Q6, load_lineitem

    p = str(tmp_path / "db")
    st = Storage(p)
    s = Session(st)
    load_lineitem(s, 2048)
    want_q6 = s.query(TPCH_Q6)
    want_cnt = s.query("SELECT COUNT(*) FROM lineitem")
    crash(st)

    s2 = Session(Storage(p))
    assert s2.query(TPCH_Q6) == want_q6
    assert s2.query("SELECT COUNT(*) FROM lineitem") == want_cnt


def test_auto_increment_does_not_collide_after_reopen(tmp_path):
    p = str(tmp_path / "db")
    st = Storage(p)
    s = Session(st)
    s.execute("CREATE TABLE a (id INT PRIMARY KEY AUTO_INCREMENT, v INT)")
    s.execute("INSERT INTO a (v) VALUES (1), (2), (3)")
    crash(st)

    s2 = Session(Storage(p))
    s2.execute("INSERT INTO a (v) VALUES (4)")
    ids = [r[0] for r in s2.query("SELECT id FROM a ORDER BY id")]
    assert len(ids) == len(set(ids)) == 4


def test_drop_and_truncate_do_not_resurrect(tmp_path):
    p = str(tmp_path / "db")
    st = Storage(p)
    s = Session(st)
    s.execute("CREATE TABLE d1 (id INT PRIMARY KEY, v INT)")
    s.execute("INSERT INTO d1 VALUES (1, 1)")
    s.execute("CREATE TABLE d2 (id INT PRIMARY KEY, v INT)")
    s.execute("INSERT INTO d2 VALUES (7, 7)")
    s.execute("DROP TABLE d1")
    s.execute("TRUNCATE TABLE d2")
    s.execute("INSERT INTO d2 VALUES (8, 8)")
    crash(st)

    s2 = Session(Storage(p))
    assert s2.query("SELECT * FROM d2") == [(8, 8)]
    with pytest.raises(Exception, match="unknown table"):
        s2.query("SELECT * FROM d1")


def test_uncommitted_txn_lost_orphan_locks_resolved(tmp_path):
    p = str(tmp_path / "db")
    st = Storage(p)
    s = Session(st)
    s.execute("CREATE TABLE t (id INT PRIMARY KEY, v INT)")
    s.execute("INSERT INTO t VALUES (1, 1)")
    s.execute("BEGIN")
    s.execute("INSERT INTO t VALUES (2, 2)")
    # crash with the txn open: nothing prewritten yet (buffered writes),
    # so simply lost. Also leave a dangling prewrite lock behind to prove
    # orphan resolution.
    from tidb_tpu.kv import tablecodec
    from tidb_tpu.kv.mvcc import Mutation, OP_PUT

    tid = st.catalog.table("test", "t").id
    key = tablecodec.record_key(tid, 99)
    st.kv.prewrite([Mutation(OP_PUT, key, b"\x03" + b"\x80" + b"\x00" * 7)],
                   key, st.tso.next_ts())
    crash(st)

    st2 = Storage(p)
    s2 = Session(st2)
    assert s2.query("SELECT id FROM t ORDER BY id") == [(1,)]
    assert st2.kv.all_locks() == []  # orphan rolled back at recovery


def test_checkpoint_then_reopen_via_snapshot(tmp_path):
    p = str(tmp_path / "db")
    st = Storage(p)
    s = Session(st)
    s.execute("CREATE TABLE c (id INT PRIMARY KEY, v VARCHAR(8))")
    s.execute("INSERT INTO c VALUES (1, 'x')")
    st.close()  # checkpoint: snapshot written, WAL truncated

    st2 = Storage(p)
    s2 = Session(st2)
    s2.execute("INSERT INTO c VALUES (2, 'y')")  # lands in fresh WAL
    crash(st2)

    s3 = Session(Storage(p))
    assert s3.query("SELECT id, v FROM c ORDER BY id") == [(1, "x"), (2, "y")]


def test_pending_ddl_resumes_after_crash(tmp_path):
    from tidb_tpu.ddl import DDL

    p = str(tmp_path / "db")
    st = Storage(p)
    s = Session(st)
    s.execute("CREATE TABLE r (id INT PRIMARY KEY, v INT)")
    s.execute("INSERT INTO r VALUES (1, 5), (2, 6), (3, 7)")
    info = st.catalog.table("test", "r")
    ddl = DDL(st, st.catalog)
    job = ddl.submit("add_index", "test", info, {
        "name": "iv", "columns": ["v"], "unique": True})
    ddl.step(job)  # delete-only — then the worker "dies"
    crash(st)

    st2 = Storage(p)  # recovery resumes the queued job to completion
    assert st2.ddl_jobs == []
    info2 = st2.catalog.table("test", "r")
    ix = next(x for x in info2.indices if x.name == "iv")
    assert ix.visible and ix.unique
    s2 = Session(st2)
    with pytest.raises(Exception, match="Duplicate"):
        s2.execute("INSERT INTO r VALUES (9, 5)")


def test_stats_survive_restart(tmp_path):
    p = str(tmp_path / "db")
    st = Storage(p)
    s = Session(st)
    s.execute("CREATE TABLE st1 (id INT PRIMARY KEY, v INT)")
    s.execute("INSERT INTO st1 VALUES " + ",".join(
        f"({i}, {i % 10})" for i in range(100)))
    s.execute("ANALYZE TABLE st1")
    tid = st.catalog.table("test", "st1").id
    assert st.stats.table_stats(tid) is not None
    crash(st)

    st2 = Storage(p)
    ts = st2.stats.table_stats(tid)
    assert ts is not None and ts.row_count == 100


def test_tso_monotonic_across_restart(tmp_path):
    p = str(tmp_path / "db")
    st = Storage(p)
    s = Session(st)
    s.execute("CREATE TABLE m (id INT PRIMARY KEY)")
    s.execute("INSERT INTO m VALUES (1)")
    last = st.tso.current()
    crash(st)

    st2 = Storage(p)
    assert st2.tso.next_ts() > last


def test_recovery_idempotent_checkpoint_crash_loop(tmp_path):
    """Property-style: checkpoint() -> simulated crash (reopen from
    disk) in a loop, with writes interleaved between crashes, stays
    byte-identical to an uncrashed in-memory oracle applying the same
    operations. Catches one-round recovery bugs that only compound
    across repeated kills (double-fold, epoch/WAL seam drift,
    resurrection after delete)."""
    import random

    rng = random.Random(20260804)
    p = str(tmp_path / "db")
    st = Storage(p)
    oracle = Storage()  # in-memory twin, never crashes
    sessions = [Session(st), Session(oracle)]
    for s in sessions:
        s.execute("CREATE TABLE t (id INT PRIMARY KEY, v INT, "
                  "s VARCHAR(16))")
    live: set[int] = set()
    next_id = 0
    for round_no in range(4):
        for _ in range(25):
            op = rng.random()
            if op < 0.55 or not live:
                next_id += 1
                live.add(next_id)
                sql = (f"INSERT INTO t VALUES ({next_id}, "
                       f"{rng.randrange(1000)}, 'r{round_no}')")
            elif op < 0.8:
                victim = rng.choice(sorted(live))
                sql = (f"UPDATE t SET v = {rng.randrange(1000)} "
                       f"WHERE id = {victim}")
            else:
                victim = rng.choice(sorted(live))
                live.discard(victim)
                sql = f"DELETE FROM t WHERE id = {victim}"
            for s in sessions:
                s.execute(sql)
        if round_no % 2 == 0:
            st.checkpoint()  # epochs + folded WAL on even rounds...
        crash(st)  # ...crash either way
        st = Storage(p)
        sessions[0] = Session(st)
        q = "SELECT id, v, s FROM t ORDER BY id"
        assert sessions[0].query(q) == sessions[1].query(q), \
            f"diverged from oracle after crash round {round_no}"
    oracle.close()


def test_sync_log_interval_group_commit(tmp_path):
    """interval mode: commits inside the window share one fsync, a
    commit past the window pays it; nothing committed is lost either
    way (process-crash durability is flush-based and policy-free)."""
    p = str(tmp_path / "db")
    st = Storage(p, sync_log="interval", sync_interval_ms=50)
    s = Session(st)
    s.execute("CREATE TABLE g (id INT PRIMARY KEY)")
    for i in range(10):
        s.execute(f"INSERT INTO g VALUES ({i})")
    crash(st)
    st2 = Storage(p)
    assert Session(st2).query("SELECT COUNT(*) FROM g") == [(10,)]
    crash(st2)


def test_sync_log_validation():
    with pytest.raises(ValueError, match="sync_log"):
        Storage(sync_log="sometimes")


def test_sync_policy_interval_covers_tail_burst():
    """The group-commit window is a real bound: commits that land
    inside the interval and are followed by IDLE time still reach disk
    within ~interval via the deferred flush — not 'whenever the next
    commit happens to arrive'."""
    import time

    from tidb_tpu.kv.mvcc import SyncPolicy

    synced = []
    sp = SyncPolicy("interval", 50, lambda: synced.append(1))
    try:
        sp.mark_dirty()
        sp.boundary()  # first boundary is past the (epoch) window
        assert len(synced) == 1
        sp.mark_dirty()
        sp.boundary()  # inside the window: deferred, not dropped
        assert len(synced) == 1
        deadline = time.monotonic() + 2.0
        while len(synced) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(synced) == 2, "tail burst never flushed"
        # commit mode: every boundary syncs, and failures propagate
        def boom():
            raise OSError("disk gone")
        sp2 = SyncPolicy("commit", 50, boom)
        sp2.mark_dirty()
        with pytest.raises(OSError):
            sp2.boundary()
    finally:
        sp.close()


def test_tpch_differential_against_reopened_store(tmp_path):
    """The full mini TPC-H corpus answers identically before and after a
    restart (the strongest end-to-end recovery check)."""
    from tidb_tpu.bench.tpch_data import TPCH_DDL, generate_tpch, load_table
    from tidb_tpu.bench.tpch_queries import TPCH_QUERIES

    p = str(tmp_path / "db")
    st = Storage(p)
    s = Session(st)
    data = generate_tpch(0.002, 17)
    for tname in TPCH_DDL:
        load_table(s, tname, data[tname])
    want = {q: s.query(TPCH_QUERIES[q]) for q in ("q1", "q3", "q6", "q12")}
    crash(st)

    s2 = Session(Storage(p))
    for q, rows in want.items():
        assert s2.query(TPCH_QUERIES[q]) == rows, f"{q} diverged"
