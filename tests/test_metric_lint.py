"""Metric-hygiene lint: the registry walk + exposition validation that
keeps /metrics scrapeable as later PRs add collectors.

obs.lint_metrics checks every registered metric for help text and the
tidb_ naming convention, rejects a family registered in two
concatenated registries (duplicate families break Prometheus scrapes),
and validates the rendered text exposition itself (HELP/TYPE lines,
label syntax, numeric values, cumulative histogram buckets). Runs in
tier-1 against a fully-exercised server so the live registries — not a
synthetic sample — are what gets linted.
"""

from __future__ import annotations

from tidb_tpu import obs
from tidb_tpu.obs import Registry
from tidb_tpu.session import Session
from tidb_tpu.store.storage import Storage


def _exercised_storage() -> Storage:
    st = Storage()
    st.obs.topsql.configure(enabled=True)
    s = Session(st)
    s.execute("create table lint_t (a int primary key, b varchar(8))")
    s.execute("insert into lint_t values (1,'x'),(2,'y')")
    s.execute("select count(*), max(a) from lint_t where a >= 1")
    s.execute("set tidb_slow_log_threshold = 0")
    s.execute("select b from lint_t")
    s.execute("set tidb_slow_log_threshold = 100000")
    st.obs.events.record("breaker_trip", detail="lint")
    return st


def test_live_registries_pass_lint():
    st = _exercised_storage()
    findings = obs.lint_metrics([st.obs.metrics, obs.PROCESS_METRICS])
    assert findings == [], "\n".join(findings)


def test_lint_flags_missing_help():
    reg = Registry()
    reg.counter("tidb_helpless_total", "")
    findings = obs.lint_metrics([reg])
    assert any("missing help" in f for f in findings), findings


def test_lint_flags_bad_prefix_and_case():
    reg = Registry()
    reg.counter("queries_total", "no prefix")
    reg.gauge("tidb_BadCase", "case")
    findings = obs.lint_metrics([reg])
    assert sum("tidb_[a-z0-9_]+" in f for f in findings) == 2, findings


def test_lint_flags_cross_registry_duplicate():
    a, b = Registry(), Registry()
    a.counter("tidb_dup_total", "one")
    b.counter("tidb_dup_total", "two")
    findings = obs.lint_metrics([a, b])
    assert any("more than one" in f for f in findings), findings


def test_lint_flags_malformed_exposition():
    bad = (
        "# HELP tidb_x_total fine\n"
        "# TYPE tidb_x_total counter\n"
        'tidb_x_total{l="v"} not_a_number\n'
        "tidb_orphan_total 3\n"
    )
    findings = obs._lint_exposition(bad)
    assert any("non-numeric" in f for f in findings), findings
    assert any("orphan" in f and "TYPE" in f for f in findings), findings


def test_lint_accepts_histogram_exposition():
    reg = Registry()
    h = reg.histogram("tidb_lat_seconds", "latency")
    for v in (0.0001, 0.01, 3.0):
        h.observe(v, stage="kernel")
        h.observe(v * 2, stage="staging")
    assert obs.lint_metrics([reg]) == []


def test_lint_flags_unbounded_device_label_cardinality():
    """Any `device`/`shard` label family must stay bounded by the mesh
    size — per-device telemetry must never become per-request
    cardinality (the classic Prometheus blow-up)."""
    reg = Registry()
    g = reg.gauge("tidb_mesh_thing_bytes", "per-device thing")
    for i in range(9):
        g.set(float(i), device=f"TPU_{i}")
    findings = obs.lint_metrics([reg], device_label_cap=8)
    assert any("cardinality" in f and "device" in f
               for f in findings), findings
    # at or under the mesh size the same family is clean
    assert obs.lint_metrics([reg], device_label_cap=9) == []
    # shard labels are held to the same cap
    c = reg.counter("tidb_mesh_shard_rows_total", "per-shard rows")
    for i in range(3):
        c.inc(shard=str(i))
    assert obs.lint_metrics([reg], device_label_cap=9) == []
    findings = obs.lint_metrics([reg], device_label_cap=2)
    assert any("tidb_mesh_shard_rows_total" in f for f in findings)


def test_lint_default_cap_tracks_mesh_size():
    """Without an explicit cap the lint uses the live mesh width
    (floor 8), so an 8-device conftest run accepts 8 device labels."""
    reg = Registry()
    g = reg.gauge("tidb_mesh_dev_bytes", "per-device")
    for i in range(8):
        g.set(1.0, device=f"d{i}")
    findings = obs.lint_metrics([reg])
    assert not any("cardinality" in f for f in findings), findings


def test_inspection_rule_registry_lints_clean():
    """Every shipped inspection rule declares name/severity/reference
    (the ISSUE-10 registry contract): a rule without a reference is a
    finding the operator cannot act on."""
    from tidb_tpu import obs_inspect

    assert len(obs_inspect.RULES) >= 10, sorted(obs_inspect.RULES)
    assert obs_inspect.lint_rules() == []


def test_inspection_rule_lint_flags_bad_metadata():
    from tidb_tpu import obs_inspect

    bad = {
        "Bad Name": obs_inspect.Rule("Bad Name", "warning", "r",
                                     lambda c: []),
        "no-ref": obs_inspect.Rule("no-ref", "warning", "",
                                   lambda c: []),
        "bad-sev": obs_inspect.Rule("bad-sev", "fatal", "r",
                                    lambda c: []),
    }
    findings = obs_inspect.lint_rules(bad)
    assert any("kebab-case" in f for f in findings), findings
    assert any("missing reference" in f for f in findings), findings
    assert any("severity" in f for f in findings), findings
    # the decorator refuses bad registrations outright
    import pytest

    with pytest.raises(ValueError):
        obs_inspect.rule("x", "warning", "")(lambda c: [])
    with pytest.raises(ValueError):
        obs_inspect.rule("x", "fatal", "ref")(lambda c: [])
    with pytest.raises(ValueError):
        obs_inspect.rule("mesh-shard-skew", "warning", "ref")(
            lambda c: [])  # duplicate name


def test_metrics_schema_tables_map_to_live_families():
    """Every metrics_schema table is backed by a live registered
    counter/gauge family — no dangling tables (the ISSUE-10 tier-1
    lint)."""
    from tidb_tpu.catalog import metrics_schema as MS

    st = _exercised_storage()
    MS.ensure_schema(st)
    assert MS.lint(st) == []
    schema = st.catalog.schemas[MS.DB_NAME]
    assert set(schema.tables) == set(MS.families(st))
    # a table whose family is gone IS flagged
    any_info = next(iter(schema.tables.values()))
    schema.tables["tidb_gone_total"] = any_info
    findings = MS.lint(st)
    assert any("dangling" in f and "tidb_gone_total" in f
               for f in findings), findings


def test_registry_type_conflict_still_raises():
    # duplicate registration under a DIFFERENT type stays a hard error
    # at registration time (lint guards the cross-registry case)
    import pytest

    reg = Registry()
    reg.counter("tidb_conflict_total", "c")
    with pytest.raises(TypeError):
        reg.gauge("tidb_conflict_total", "g")
