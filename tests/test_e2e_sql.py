"""End-to-end SQL tests through the full stack (parser -> planner -> TiTPU
coprocessor -> host executor), testkit style."""

import pytest

from testkit import TestKit


@pytest.fixture
def tk():
    t = TestKit()
    t.must_exec(
        "create table t (id bigint primary key, name varchar(20), "
        "qty decimal(10,2), d date)"
    )
    t.must_exec(
        "insert into t values "
        "(1, 'alpha', 10.50, '2024-01-01'), "
        "(2, 'beta', 3.25, '2024-02-01'), "
        "(3, 'alpha', 7.00, '2024-03-01'), "
        "(4, null, null, null), "
        "(5, 'gamma', 0.75, '2024-01-15')"
    )
    return t


class TestBasicSelect:
    def test_select_all(self, tk):
        rows = tk.must_query("select * from t")
        assert len(rows) == 5

    def test_select_constant(self, tk):
        tk.check("select 1 + 2", [(3,)])
        tk.check("select 'hello'", [("hello",)])

    def test_where_filters(self, tk):
        tk.check("select id from t where qty > 5", [(1,), (3,)],
                 ordered=False)
        tk.check("select id from t where name = 'alpha'", [(1,), (3,)],
                 ordered=False)
        tk.check("select id from t where name <> 'alpha' and qty < 1",
                 [(5,)])
        tk.check("select id from t where d >= '2024-02-01'", [(2,), (3,)],
                 ordered=False)

    def test_null_semantics(self, tk):
        tk.check("select id from t where name is null", [(4,)])
        tk.check("select id from t where name is not null",
                 [(1,), (2,), (3,), (5,)], ordered=False)
        # NULL never matches comparisons
        tk.check("select id from t where qty <> 3.25", [(1,), (3,), (5,)],
                 ordered=False)

    def test_in_between_like(self, tk):
        tk.check("select id from t where id in (2, 4)", [(2,), (4,)],
                 ordered=False)
        tk.check("select id from t where qty between 3 and 8",
                 [(2,), (3,)], ordered=False)
        tk.check("select id from t where name like 'a%'", [(1,), (3,)],
                 ordered=False)
        tk.check("select id from t where name like '%mm%'", [(5,)])

    def test_projection_arith(self, tk):
        tk.check("select id * 2 + 1 from t where id = 3", [(7,)])
        tk.check("select qty * 2 from t where id = 1", [("21.00",)])
        tk.check("select qty * qty from t where id = 2", [("10.5625",)])

    def test_order_by_limit(self, tk):
        tk.check("select id from t order by qty desc limit 2",
                 [(1,), (3,)])
        tk.check("select id from t order by qty limit 2", [(4,), (5,)])
        # name desc: gamma(5), beta(2), alpha(3,1), NULL(4) last
        tk.check("select id from t order by name desc, id desc limit 3",
                 [(5,), (2,), (3,)])
        tk.check("select id from t order by id limit 2 offset 2",
                 [(3,), (4,)])

    def test_order_by_alias_and_position(self, tk):
        tk.check("select id i from t order by i desc limit 1", [(5,)])
        tk.check("select id, qty from t order by 2 limit 1", [(4, None)])


class TestAggregation:
    def test_scalar_aggs(self, tk):
        tk.check("select count(*), count(qty), sum(qty), min(qty), max(qty) "
                 "from t",
                 [(5, 4, "21.50", "0.75", "10.50")])

    def test_avg_decimal_scale(self, tk):
        tk.check("select avg(qty) from t", [("5.375000",)])

    def test_group_by(self, tk):
        tk.check(
            "select name, count(*), sum(qty) from t group by name "
            "order by name",
            [(None, 1, None), ("alpha", 2, "17.50"), ("beta", 1, "3.25"),
             ("gamma", 1, "0.75")],
        )

    def test_group_by_having(self, tk):
        tk.check(
            "select name, count(*) c from t group by name having c > 1",
            [("alpha", 2)],
        )

    def test_empty_table_aggs(self, tk):
        tk.must_exec("create table e (x bigint, y decimal(8,2))")
        tk.check("select count(*), sum(y), avg(y) from e", [(0, None, None)])

    def test_count_distinct(self, tk):
        tk.check("select count(distinct name) from t", [(3,)])

    def test_distinct(self, tk):
        tk.check("select distinct name from t",
                 [(None,), ("alpha",), ("beta",), ("gamma",)], ordered=False)

    def test_group_by_int_host_fallback(self, tk):
        # int group keys take the host fallback path (dense gate)
        tk.check(
            "select id % 2, count(*) from t group by id % 2 order by 1",
            [(0, 2), (1, 3)],
        )


class TestJoins:
    @pytest.fixture
    def tk2(self, tk):
        tk.must_exec("create table o (oid bigint, tid bigint, v bigint)")
        tk.must_exec(
            "insert into o values (100, 1, 7), (101, 1, 8), (102, 3, 9), "
            "(103, 99, 0)"
        )
        return tk

    def test_inner_join(self, tk2):
        tk2.check(
            "select t.id, o.oid from t join o on t.id = o.tid order by o.oid",
            [(1, 100), (1, 101), (3, 102)],
        )

    def test_left_join(self, tk2):
        tk2.check(
            "select t.id, o.oid from t left join o on t.id = o.tid "
            "order by t.id, o.oid",
            [(1, 100), (1, 101), (2, None), (3, 102), (4, None), (5, None)],
        )

    def test_comma_join_with_where(self, tk2):
        tk2.check(
            "select t.id, o.v from t, o where t.id = o.tid and o.v > 7 "
            "order by o.v",
            [(1, 8), (3, 9)],
        )

    def test_join_agg(self, tk2):
        tk2.check(
            "select t.name, sum(o.v) from t join o on t.id = o.tid "
            "group by t.name order by t.name",
            [("alpha", 24)],
        )


class TestDML:
    def test_update(self, tk):
        tk.must_exec("update t set qty = qty + 1 where id = 2")
        tk.check("select qty from t where id = 2", [("4.25",)])

    def test_update_all(self, tk):
        tk.must_exec("update t set name = 'x'")
        tk.check("select count(distinct name) from t", [(1,)])

    def test_delete(self, tk):
        tk.must_exec("delete from t where qty < 5")
        tk.check("select id from t order by id", [(1,), (3,), (4,)])

    def test_insert_select(self, tk):
        tk.must_exec("create table t2 (id bigint, qty decimal(10,2))")
        tk.must_exec("insert into t2 select id, qty from t where qty > 5")
        tk.check("select count(*) from t2", [(2,)])

    def test_replace_into_and_autoinc(self, tk):
        tk.must_exec(
            "create table ai (id bigint primary key auto_increment, "
            "v varchar(5))")
        tk.must_exec("insert into ai (v) values ('a'), ('b')")
        rows = tk.must_query("select id from ai order by id")
        assert rows[0][0] < rows[1][0]


class TestTransactions:
    def test_rollback(self, tk):
        tk.must_exec("begin")
        tk.must_exec("insert into t values (10, 'tx', 1.00, null)")
        tk.check("select count(*) from t", [(6,)])  # read-your-writes
        tk.must_exec("rollback")
        tk.check("select count(*) from t", [(5,)])

    def test_commit(self, tk):
        tk.must_exec("begin")
        tk.must_exec("insert into t values (10, 'tx', 1.00, null)")
        tk.must_exec("commit")
        tk.check("select count(*) from t", [(6,)])

    def test_snapshot_isolation_across_sessions(self, tk):
        from tidb_tpu.session import Session
        s2 = Session(tk.session.storage)
        tk.must_exec("begin")
        tk.check("select count(*) from t", [(5,)])
        s2.execute("insert into t values (11, 'other', 2.00, null)")
        # our txn still sees the old snapshot
        tk.check("select count(*) from t", [(5,)])
        tk.must_exec("commit")
        tk.check("select count(*) from t", [(6,)])


class TestDDL:
    def test_show_tables(self, tk):
        rows = tk.must_query("show tables")
        assert ("t",) in rows

    def test_drop_and_recreate(self, tk):
        tk.must_exec("drop table t")
        with pytest.raises(Exception):
            tk.must_query("select * from t")
        tk.must_exec("create table t (a bigint)")
        tk.check("select count(*) from t", [(0,)])

    def test_truncate(self, tk):
        tk.must_exec("truncate table t")
        tk.check("select count(*) from t", [(0,)])

    def test_explain(self, tk):
        rows = tk.must_query("explain select sum(qty) from t where id > 1")
        text = "\n".join(r[0] for r in rows)
        assert "TableRead[TiTPU]" in text
        assert "agg" in text


class TestExpressions:
    def test_case_when(self, tk):
        tk.check(
            "select id, case when qty > 5 then 'big' when qty is null "
            "then 'none' else 'small' end from t order by id",
            [(1, "big"), (2, "small"), (3, "big"), (4, "none"), (5, "small")],
        )

    def test_cast(self, tk):
        tk.check("select cast(qty as signed) from t where id = 1", [(11,)])
        tk.check("select cast(id as decimal(10,2)) from t where id = 3",
                 [("3.00",)])

    def test_date_functions(self, tk):
        tk.check("select year(d), month(d), day(d) from t where id = 2",
                 [(2024, 2, 1)])

    def test_if_ifnull_coalesce(self, tk):
        tk.check("select ifnull(name, 'missing') from t where id = 4",
                 [("missing",)])
        tk.check("select if(qty > 5, id, 0 - id) from t where id = 2",
                 [(-2,)])
        tk.check("select coalesce(qty, 0) from t where id = 4", [("0.00",)])


class TestReviewRegressions:
    """Regressions from code review of the end-to-end slice."""

    def test_update_string_literal(self, tk):
        tk.must_exec("update t set name = 'newval' where id = 1")
        tk.check("select name from t where id = 1", [("newval",)])

    def test_stale_string_predicate_after_dict_growth(self, tk):
        tk.check("select id from t where name = 'zed'", [])
        tk.must_exec("insert into t values (9, 'zed', 1.00, null)")
        tk.session.storage.flush()
        tk.check("select id from t where name = 'zed'", [(9,)])

    def test_topn_across_epoch_and_overlay(self, tk):
        tk.session.storage.flush()  # move fixture rows into the base epoch
        tk.must_exec("insert into t values (6, 'x', 0.10, null), "
                     "(7, 'y', 99.00, null)")
        # base epoch rows and fresh overlay rows must merge correctly
        tk.check("select id from t order by qty desc limit 2", [(7,), (1,)])
        tk.check("select id from t order by qty limit 2", [(4,), (6,)])

    def test_float_decimal_sci_notation_ingest(self, tk):
        tk.must_exec("create table f (x decimal(10,2))")
        tk.must_exec("insert into f values (1e-05), (2.5e2)")
        tk.check("select x from f order by x", [("0.00",), ("250.00",)])

    def test_distinct_float_aggs(self, tk):
        tk.must_exec("create table fl (g bigint, v double)")
        tk.must_exec("insert into fl values (1, 1.2), (1, 1.5), (1, 1.2)")
        tk.check("select count(distinct v) from fl", [(2,)])

    def test_int_float_join_keys(self, tk):
        tk.must_exec("create table a1 (k bigint)")
        tk.must_exec("create table b1 (k double)")
        tk.must_exec("insert into a1 values (5), (6)")
        tk.must_exec("insert into b1 values (5.0), (7.0)")
        tk.check("select a1.k from a1 join b1 on a1.k = b1.k", [(5,)])

    def test_update_decimal_scale_mismatch(self, tk):
        # qty*qty has scale 4; column scale is 2 -> must round-rescale
        tk.must_exec("update t set qty = qty * qty where id = 2")
        tk.check("select qty from t where id = 2", [("10.56",)])

    def test_topn_over_computed_sort_key(self, tk):
        # sort key references the pushed projection's output, not scan cols
        tk.must_exec("create table sx (a bigint, b bigint)")
        tk.must_exec("insert into sx values (1, 100), (2, 0), (3, 50)")
        tk.check("select a + b sm from sx order by sm limit 1", [(2,)])
        tk.check("select a + b sm from sx order by sm desc limit 2",
                 [(101,), (53,)])

    def test_string_col_eq_col_cross_dict(self, tk):
        tk.must_exec("create table u2 (x varchar(5), y varchar(5))")
        tk.must_exec("insert into u2 values ('a','b'), ('c','c'), ('b','a')")
        tk.check("select x from u2 where x = y", [("c",)])

    def test_decimal_in_list_scales(self, tk):
        tk.check("select id from t where qty in (10.5, 3.250, 99)",
                 [(1,), (2,)], ordered=False)
        # over-precise value can never match a scale-2 column
        tk.check("select id from t where qty in (10.505)", [])

    def test_topn_desc_nulls_fill_limit(self, tk):
        # NULL keys sort last under DESC but still satisfy the LIMIT
        tk.check("select id from t where id > 1 order by qty desc limit 4",
                 [(3,), (2,), (5,), (4,)])
