"""Window frames + ranking breadth, differential against sqlite.

Counterpart of the reference's window executor tests
(executor/window_test.go; frame processors in executor/window.go).
sqlite implements SQL window frames, so it serves as the oracle the
same way it does for the TPC-H suite."""

from __future__ import annotations

import sqlite3

import numpy as np
import pytest

from testkit import TestKit


def _dataset(tk: TestKit, conn):
    tk.must_exec("create table wf (g int, k int, v int, d decimal(8,2))")
    conn.execute("create table wf (g int, k int, v int, d real)")
    rng = np.random.default_rng(23)
    rows = []
    for i in range(300):
        g = int(rng.integers(0, 5))
        k = int(rng.integers(0, 40))
        v = int(rng.integers(-50, 50))
        d = round(float(rng.random() * 100), 2)
        rows.append((g, k, v, d))
    tk.must_exec("insert into wf values " + ",".join(
        f"({g},{k},{v},{d})" for g, k, v, d in rows))
    conn.executemany("insert into wf values (?,?,?,?)", rows)
    conn.commit()


QUERIES = [
    # ROWS frames over aggregates
    "select g, k, v, sum(v) over (partition by g order by k, v "
    "rows between 2 preceding and current row) from wf order by g, k, v",
    "select g, k, v, count(*) over (partition by g order by k, v "
    "rows between 1 preceding and 3 following) from wf order by g, k, v",
    "select g, k, v, min(v) over (partition by g order by k, v "
    "rows between 4 preceding and 1 preceding) from wf order by g, k, v",
    "select g, k, v, max(v) over (partition by g order by k, v "
    "rows between current row and unbounded following) from wf "
    "order by g, k, v",
    "select g, k, v, avg(v) over (partition by g order by k, v "
    "rows between 1 preceding and 1 following) from wf order by g, k, v",
    # RANGE with value offsets
    "select g, k, v, sum(v) over (partition by g order by k "
    "range between 3 preceding and 3 following) from wf order by g, k, v",
    "select g, k, v, count(*) over (partition by g order by k "
    "range between 5 preceding and current row) from wf order by g, k, v",
    # value functions over frames
    "select g, k, v, first_value(v) over (partition by g order by k, v "
    "rows between 2 preceding and 1 following) from wf order by g, k, v",
    "select g, k, v, last_value(v) over (partition by g order by k, v "
    "rows between 2 preceding and 1 following) from wf order by g, k, v",
    "select g, k, v, nth_value(v, 2) over (partition by g order by k, v "
    "rows between 2 preceding and 2 following) from wf order by g, k, v",
    # ranking breadth
    "select g, k, v, ntile(4) over (partition by g order by k, v) "
    "from wf order by g, k, v",
    "select g, k, v, percent_rank() over (partition by g order by k, v) "
    "from wf order by g, k, v",
    "select g, k, v, cume_dist() over (partition by g order by k, v) "
    "from wf order by g, k, v",
    # descending order with frames
    "select g, k, v, sum(v) over (partition by g order by k desc, v desc "
    "rows between 1 preceding and 1 following) from wf order by g, k, v",
    "select g, k, v, sum(v) over (partition by g order by k desc "
    "range between 2 preceding and current row) from wf order by g, k, v",
]


def _norm(v):
    if v is None:
        return None
    if hasattr(v, "to_float"):  # engine Decimal (AVG yields scale 4)
        v = v.to_float()
    if isinstance(v, float):
        return round(v, 3)
    try:
        return round(float(v), 3)
    except (TypeError, ValueError):
        return v


@pytest.mark.parametrize("qi", range(len(QUERIES)))
def test_frames_match_sqlite(qi):
    tk = TestKit()
    conn = sqlite3.connect(":memory:")
    _dataset(tk, conn)
    q = QUERIES[qi]
    got = [tuple(_norm(c) for c in r) for r in tk.must_query(q)]
    want = [tuple(_norm(c) for c in r) for r in conn.execute(q).fetchall()]
    assert got == want, f"mismatch on: {q}\n got: {got[:5]}\nwant: {want[:5]}"


def test_frame_over_decimal_range():
    """RANGE offsets on a DECIMAL ORDER BY key scale to the column's
    fraction digits (offset 3 means 3.00)."""
    tk = TestKit()
    tk.must_exec("create table dd (k decimal(6,2), v int)")
    tk.must_exec("insert into dd values (1.00, 1), (2.50, 2), (3.90, 3), "
                 "(7.00, 4)")
    r = tk.must_query(
        "select v, sum(v) over (order by k range between 2 preceding "
        "and current row) from dd order by k")
    # k=1.00 -> [1]; k=2.50 -> [1,2]; k=3.90 -> [2,3] (1.90..3.90);
    # k=7.00 -> [4] (5.00..7.00)
    assert r == [(1, 1), (2, 3), (3, 5), (4, 4)]


def test_frame_parse_errors():
    tk = TestKit()
    tk.must_exec("create table pe (a int, b varchar(8))")
    tk.must_exec("insert into pe values (1, 'x')")
    with pytest.raises(Exception, match="numeric ORDER BY|requires"):
        tk.must_query("select sum(a) over (order by b range between 1 "
                      "preceding and current row) from pe")
    with pytest.raises(Exception, match="invalid window frame"):
        tk.must_query("select sum(a) over (order by a rows between "
                      "unbounded following and current row) from pe")
