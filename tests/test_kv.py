"""KV tier tests: codecs, tablecodec, percolator MVCC, regions, 2PC.

Mirrors the reference's coverage shape (reference: util/codec/codec_test.go
ordering properties; store/mockstore/mocktikv/mvcc_test patterns;
store/tikv/2pc_test.go commit/rollback/resolve scenarios;
region_cache_test.go split+retry).
"""

from __future__ import annotations

import threading

import pytest

from tidb_tpu.kv import codec, tablecodec
from tidb_tpu.kv.mvcc import (
    OP_DEL,
    OP_PUT,
    KeyIsLockedError,
    MVCCStore,
    Mutation,
    TxnNotFoundError,
    WriteConflictError,
)
from tidb_tpu.kv.native import NativeOrderedKV, native_available
from tidb_tpu.kv.region import RegionError, RegionManager
from tidb_tpu.kv.twopc import TSO, Snapshot, TwoPhaseCommitter

ENGINES = ["py"] + (["native"] if native_available() else [])


@pytest.fixture(params=ENGINES)
def store(request) -> MVCCStore:
    """Percolator store over both substrates: pure-Python ordered KV and
    the C++ engine (native/kvstore.cpp) — identical semantics required."""
    if request.param == "native":
        return MVCCStore(NativeOrderedKV())
    return MVCCStore()


# ---------------------------------------------------------------------------
# codecs
# ---------------------------------------------------------------------------

class TestCodec:
    def test_int_order(self):
        vals = [-(2**62), -1000, -1, 0, 1, 7, 2**62]
        encs = [codec.encode_key([v]) for v in vals]
        assert encs == sorted(encs)
        for v, e in zip(vals, encs):
            assert codec.decode_key(e) == [v]

    def test_float_order(self):
        vals = [-1e300, -2.5, -0.0, 0.0, 1e-9, 3.14, 1e300]
        encs = [codec.encode_key([v]) for v in vals]
        assert sorted(encs) == encs

    def test_bytes_order_and_roundtrip(self):
        vals = [b"", b"a", b"ab", b"abcdefgh", b"abcdefghi", b"b"]
        encs = [codec.encode_key([v]) for v in vals]
        assert sorted(encs) == encs
        for v, e in zip(vals, encs):
            assert codec.decode_key(e) == [v]

    def test_bytes_with_zeros(self):
        vals = [b"a\x00\x00", b"a\x00b", b"a\x01"]
        for v in vals:
            assert codec.decode_key(codec.encode_key([v])) == [v]
        encs = [codec.encode_key([v]) for v in vals]
        assert sorted(encs) == encs

    def test_composite_keys(self):
        a = codec.encode_key([1, "apple", 3])
        b = codec.encode_key([1, "apple", 4])
        c = codec.encode_key([1, "banana", 0])
        d = codec.encode_key([2, "", 0])
        assert a < b < c < d
        assert codec.decode_key(b) == [1, b"apple", 4]

    def test_null_sorts_first(self):
        assert codec.encode_key([None]) < codec.encode_key([-(2**62)])
        assert codec.decode_key(codec.encode_key([None])) == [None]


class TestTableCodec:
    def test_record_key_roundtrip(self):
        k = tablecodec.record_key(42, 7)
        assert tablecodec.decode_record_key(k) == (42, 7)

    def test_record_keys_cluster_by_table(self):
        ks = [tablecodec.record_key(t, h)
              for t in (1, 2) for h in (-5, 0, 9)]
        assert ks == sorted(ks)

    def test_index_key_order(self):
        a = tablecodec.index_key(1, 1, ["ann", 1], 10)
        b = tablecodec.index_key(1, 1, ["bob", 0], 3)
        assert a < b

    def test_table_range_covers(self):
        s, e = tablecodec.table_range(5)
        assert s <= tablecodec.record_key(5, 0) < e
        assert s <= tablecodec.index_key(5, 2, [1]) < e
        assert not (s <= tablecodec.record_key(6, 0) < e)


# ---------------------------------------------------------------------------
# percolator MVCC
# ---------------------------------------------------------------------------

def put(k: bytes, v: bytes) -> Mutation:
    return Mutation(OP_PUT, k, v)


def dele(k: bytes) -> Mutation:
    return Mutation(OP_DEL, k)


class TestMVCC:
    def test_snapshot_isolation(self, store):
        s = store
        s.prewrite([put(b"k", b"v1")], b"k", start_ts=10)
        s.commit([b"k"], 10, 11)
        s.prewrite([put(b"k", b"v2")], b"k", start_ts=20)
        s.commit([b"k"], 20, 21)
        assert s.get(b"k", 11) == b"v1"
        assert s.get(b"k", 15) == b"v1"
        assert s.get(b"k", 21) == b"v2"
        assert s.get(b"k", 9) is None

    def test_delete_visibility(self, store):
        s = store
        s.prewrite([put(b"k", b"v")], b"k", 10)
        s.commit([b"k"], 10, 11)
        s.prewrite([dele(b"k")], b"k", 20)
        s.commit([b"k"], 20, 21)
        assert s.get(b"k", 15) == b"v"
        assert s.get(b"k", 25) is None

    def test_write_conflict(self, store):
        s = store
        s.prewrite([put(b"k", b"v1")], b"k", 10)
        s.commit([b"k"], 10, 15)
        with pytest.raises(WriteConflictError):
            s.prewrite([put(b"k", b"v2")], b"k", start_ts=12)

    def test_read_blocked_by_lock(self, store):
        s = store
        s.prewrite([put(b"k", b"v")], b"k", 10)
        with pytest.raises(KeyIsLockedError):
            s.get(b"k", 15)
        assert s.get(b"k", 9) is None  # older reads pass the lock

    def test_rollback_then_late_commit_fails(self, store):
        s = store
        s.prewrite([put(b"k", b"v")], b"k", 10)
        s.rollback([b"k"], 10)
        with pytest.raises(TxnNotFoundError):
            s.commit([b"k"], 10, 12)
        assert s.get(b"k", 20) is None

    def test_rollback_marker_blocks_late_prewrite(self, store):
        s = store
        s.rollback([b"k"], 10)  # marker for a txn that never prewrote here
        with pytest.raises(WriteConflictError):
            s.prewrite([put(b"k", b"v")], b"k", start_ts=10)

    def test_commit_idempotent(self, store):
        s = store
        s.prewrite([put(b"k", b"v")], b"k", 10)
        s.commit([b"k"], 10, 11)
        s.commit([b"k"], 10, 11)  # retry after lost response: no error
        assert s.get(b"k", 12) == b"v"

    def test_scan_snapshot(self, store):
        s = store
        for i, ts in ((1, 10), (2, 20), (3, 30)):
            k = b"k%d" % i
            s.prewrite([put(k, b"v%d" % i)], k, ts)
            s.commit([k], ts, ts + 1)
        assert s.scan(b"k", b"l", read_ts=25) == [
            (b"k1", b"v1"), (b"k2", b"v2")]
        assert s.scan(b"k", b"l", read_ts=100, limit=1) == [(b"k1", b"v1")]

    def test_check_txn_status_committed(self, store):
        s = store
        s.prewrite([put(b"p", b"v"), put(b"s", b"w")], b"p", 10)
        s.commit([b"p"], 10, 11)  # primary committed, secondary still locked
        commit_ts, done = s.check_txn_status(b"p", 10, current_ts=10**18)
        assert done and commit_ts == 11
        s.resolve_lock(b"s", 10, commit_ts)  # roll forward
        assert s.get(b"s", 12) == b"w"

    def test_check_txn_status_expired_rolls_back(self, store):
        s = store
        s.prewrite([put(b"p", b"v")], b"p", 10, ttl=1)
        commit_ts, done = s.check_txn_status(b"p", 10, current_ts=10**18)
        assert done and commit_ts == 0
        assert s.get(b"p", 20) is None

    def test_gc_drops_old_versions(self, store):
        s = store
        for ts in (10, 20, 30):
            s.prewrite([put(b"k", b"v%d" % ts)], b"k", ts)
            s.commit([b"k"], ts, ts + 1)
        removed = s.gc(safepoint=25)
        assert removed >= 1
        assert s.get(b"k", 100) == b"v30"  # newest survives


# ---------------------------------------------------------------------------
# regions + 2PC
# ---------------------------------------------------------------------------

class TestRegions:
    def test_locate_and_split(self):
        rm = RegionManager()
        r0 = rm.locate(b"m")
        assert r0.start_key == b"" and r0.end_key == b""
        left, right = rm.split(b"m")
        assert rm.locate(b"a").id == left.id
        assert rm.locate(b"m").id == right.id
        assert rm.locate(b"z").id == right.id

    def test_stale_epoch_rejected(self):
        rm = RegionManager()
        stale = rm.locate(b"k")
        rm.split(b"m")  # bumps epoch of the left region
        with pytest.raises(RegionError):
            rm.check_context(stale.id, stale.epoch, [b"k"])

    def test_key_out_of_range_rejected(self):
        rm = RegionManager()
        rm.split(b"m")
        left = rm.locate(b"a")
        with pytest.raises(RegionError):
            rm.check_context(left.id, left.epoch, [b"z"])


class Test2PC:
    def test_commit_across_regions(self):
        rm = RegionManager()
        rm.split(b"m")
        tso = TSO()
        c = TwoPhaseCommitter(rm, tso)
        start = tso.ts()
        commit_ts = c.commit(
            [put(b"a", b"1"), put(b"z", b"2")], start)
        snap = Snapshot(rm, tso, commit_ts + 1)
        assert snap.get(b"a") == b"1"
        assert snap.get(b"z") == b"2"

    def test_commit_survives_concurrent_split(self):
        rm = RegionManager()
        tso = TSO()
        c = TwoPhaseCommitter(rm, tso)
        keys = [b"k%03d" % i for i in range(40)]

        stop = threading.Event()

        def splitter():
            i = 0
            while not stop.is_set() and i < 20:
                rm.split(b"k%03d" % (i * 2 + 1))
                i += 1

        t = threading.Thread(target=splitter)
        t.start()
        try:
            for n, k in enumerate(keys):
                start = tso.ts()
                c.commit([put(k, b"v%d" % n)], start)
        finally:
            stop.set()
            t.join()
        snap = Snapshot(rm, tso, tso.ts())
        for n, k in enumerate(keys):
            assert snap.get(k) == b"v%d" % n

    def test_reader_resolves_crashed_committed_txn(self):
        """Primary committed, coordinator died before secondaries: reader
        must roll the secondary forward (reference: lock_resolver.go)."""
        rm = RegionManager()
        tso = TSO()
        start = tso.ts()
        rm.store.prewrite([put(b"p", b"v"), put(b"s", b"w")], b"p", start)
        commit_ts = tso.ts()
        rm.store.commit([b"p"], start, commit_ts)
        # coordinator crashes here; a reader arrives
        snap = Snapshot(rm, tso, tso.ts())
        assert snap.get(b"s") == b"w"

    def test_reader_rolls_back_crashed_uncommitted_txn(self):
        rm = RegionManager()
        tso = TSO()
        start = tso.ts()
        rm.store.prewrite([put(b"p", b"v"), put(b"s", b"w")], b"p", start,
                          ttl=0)  # instantly expired
        snap = Snapshot(rm, tso, tso.ts())
        assert snap.get(b"s") is None
        assert snap.get(b"p") is None

    def test_concurrent_commits_conflict(self):
        rm = RegionManager()
        tso = TSO()
        c = TwoPhaseCommitter(rm, tso)
        s1 = tso.ts()
        s2 = tso.ts()
        c.commit([put(b"k", b"first")], s1)
        with pytest.raises(Exception):
            c.commit([put(b"k", b"second")], s2)  # started before s1 landed

    def test_rollback_path(self):
        rm = RegionManager()
        tso = TSO()
        c = TwoPhaseCommitter(rm, tso)
        start = tso.ts()
        muts = [put(b"a", b"1"), put(b"b", b"2")]
        rm.store.prewrite(muts, b"a", start)
        c.rollback(muts, start)
        snap = Snapshot(rm, tso, tso.ts())
        assert snap.get(b"a") is None
        assert snap.get(b"b") is None
