"""Two servers, one database: the multi-process plane.

The reference's cluster shape — many tidb-servers over shared storage —
verified with REAL processes: schema changes made through one server are
visible on the other without restart (domain reload analog,
domain/domain.go:352), a transaction planned against a superseded schema
aborts at commit (schema validator, domain/schema_validator.go), and a
query on one server can be killed from the other
(tests/globalkilltest; server/server.go:548 Kill).
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time

import pytest

sys.path.insert(0, os.path.dirname(__file__))
from mysql_client import MiniClient, MySQLError  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SERVER_SRC = """
import sys
sys.path.insert(0, {repo!r})
from tidb_tpu.server.server import Server
from tidb_tpu.store.storage import Storage

storage = Storage({path!r}, shared=True)
srv = Server(storage, host="127.0.0.1", port=0)
srv.start()
print(f"PORT={{srv.port}}", flush=True)
import time
while True:
    time.sleep(1)
"""


def _spawn(path: str) -> tuple[subprocess.Popen, int]:
    proc = subprocess.Popen(
        [sys.executable, "-c", SERVER_SRC.format(repo=REPO, path=path)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    deadline = time.time() + 60
    port = None
    while time.time() < deadline:
        line = proc.stdout.readline()
        if line.startswith("PORT="):
            port = int(line.strip().split("=")[1])
            break
        if proc.poll() is not None:
            raise RuntimeError("server died during startup")
    assert port, "server did not report a port"
    return proc, port


@pytest.fixture()
def cluster(tmp_path):
    procs = []
    try:
        a, pa = _spawn(str(tmp_path))
        procs.append(a)
        b, pb = _spawn(str(tmp_path))
        procs.append(b)
        ca = MiniClient("127.0.0.1", pa)
        cb = MiniClient("127.0.0.1", pb)
        yield ca, cb
        ca.close()
        cb.close()
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def test_ddl_and_data_visible_across_servers(cluster):
    ca, cb = cluster
    ca.execute("create table t (id bigint primary key, v bigint)")
    ca.execute("insert into t values (1, 10), (2, 20)")
    # DDL + rows made through A are served by B without restart
    rows = cb.query("select id, v from t order by id")
    assert rows == [("1", "10"), ("2", "20")]
    cb.execute("insert into t values (3, 30)")
    rows = ca.query("select sum(v) from t")
    assert rows == [("60",)]
    # second round of DDL: B adds a column, A uses it immediately
    cb.execute("alter table t add column w bigint")
    ca.execute("update t set w = id * 100 where id = 1")
    assert cb.query("select w from t where id = 1") == [("100",)]


def test_stale_schema_commit_aborts(cluster):
    ca, cb = cluster
    ca.execute("create table f (id bigint primary key, v bigint)")
    ca.execute("insert into f values (1, 1)")
    # B buffers a write under the current schema...
    cb.execute("begin")
    cb.execute("update f set v = 2 where id = 1")
    # ...A rewrites the table layout...
    ca.execute("alter table f add column extra bigint")
    # ...B's commit must abort at the schema fence
    with pytest.raises(MySQLError) as exc:
        cb.execute("commit")
    assert "schema" in str(exc.value).lower() or \
        "try again" in str(exc.value).lower()
    # and the row kept its pre-txn value
    assert ca.query("select v from f") == [("1",)]


def test_conflicting_writes_across_servers(cluster):
    ca, cb = cluster
    ca.execute("create table c (id bigint primary key, v bigint)")
    ca.execute("insert into c values (1, 0)")
    # sequential increments alternating between servers stay exact
    for i in range(6):
        cli = ca if i % 2 == 0 else cb
        cli.execute("update c set v = v + 1 where id = 1")
    assert ca.query("select v from c") == [("6",)]
    assert cb.query("select v from c") == [("6",)]


def test_global_kill_from_sibling(cluster):
    ca, cb = cluster
    conn_id = int(cb.query("select connection_id()")[0][0])
    errs: list = []

    def long_query():
        try:
            cb.query("select sleep(25)")  # interruptible, like MySQL's
        except MySQLError as e:
            errs.append(e)

    t = threading.Thread(target=long_query)
    t.start()
    time.sleep(1.0)
    t0 = time.time()
    ca.execute(f"kill query {conn_id}")
    t.join(timeout=20)
    assert not t.is_alive(), "query was not killed"
    assert time.time() - t0 < 15, "kill took too long"
    assert errs and "interrupt" in str(errs[0]).lower()
    # connection survives a QUERY kill
    assert cb.query("select 1") == [("1",)]
