"""Two servers, one database: the multi-process plane.

The reference's cluster shape — many tidb-servers over shared storage —
verified with REAL processes: schema changes made through one server are
visible on the other without restart (domain reload analog,
domain/domain.go:352), a transaction planned against a superseded schema
aborts at commit (schema validator, domain/schema_validator.go), and a
query on one server can be killed from the other
(tests/globalkilltest; server/server.go:548 Kill).
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time

import pytest

sys.path.insert(0, os.path.dirname(__file__))
from mysql_client import MiniClient, MySQLError  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SERVER_SRC = """
import os, sys
sys.path.insert(0, {repo!r})
from tidb_tpu.server.server import Server
from tidb_tpu.store.storage import Storage

fp = os.environ.get("TIDB_TPU_CRASH_FP")
if fp:  # hard-kill this server at a named 2PC point (crash testing)
    from tidb_tpu.util import failpoint
    failpoint.enable(fp, lambda: os._exit(9))
storage = Storage({path!r}, shared=True)
srv = Server(storage, host="127.0.0.1", port=0)
srv.start()
print(f"PORT={{srv.port}}", flush=True)
import time
while True:
    time.sleep(1)
"""


def _spawn(path: str, crash_fp: str | None = None
           ) -> tuple[subprocess.Popen, int]:
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    if crash_fp:
        env["TIDB_TPU_CRASH_FP"] = crash_fp
    proc = subprocess.Popen(
        [sys.executable, "-c", SERVER_SRC.format(repo=REPO, path=path)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env)
    # readiness = the PORT= line; the deadline is only a backstop and is
    # sized for a loaded single-core machine (round-4 flake: 60s)
    deadline = time.time() + 180
    port = None
    while time.time() < deadline:
        line = proc.stdout.readline()
        if line.startswith("PORT="):
            port = int(line.strip().split("=")[1])
            break
        if proc.poll() is not None:
            raise RuntimeError("server died during startup")
    assert port, "server did not report a port"
    return proc, port


@pytest.fixture()
def cluster(tmp_path):
    procs = []
    try:
        a, pa = _spawn(str(tmp_path))
        procs.append(a)
        b, pb = _spawn(str(tmp_path))
        procs.append(b)
        ca = MiniClient("127.0.0.1", pa)
        cb = MiniClient("127.0.0.1", pb)
        yield ca, cb
        ca.close()
        cb.close()
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def test_ddl_and_data_visible_across_servers(cluster):
    ca, cb = cluster
    ca.execute("create table t (id bigint primary key, v bigint)")
    ca.execute("insert into t values (1, 10), (2, 20)")
    # DDL + rows made through A are served by B without restart
    rows = cb.query("select id, v from t order by id")
    assert rows == [("1", "10"), ("2", "20")]
    cb.execute("insert into t values (3, 30)")
    rows = ca.query("select sum(v) from t")
    assert rows == [("60",)]
    # second round of DDL: B adds a column, A uses it immediately
    cb.execute("alter table t add column w bigint")
    ca.execute("update t set w = id * 100 where id = 1")
    assert cb.query("select w from t where id = 1") == [("100",)]


def test_stale_schema_commit_aborts(cluster):
    ca, cb = cluster
    ca.execute("create table f (id bigint primary key, v bigint)")
    ca.execute("insert into f values (1, 1)")
    # B buffers a write under the current schema...
    cb.execute("begin")
    cb.execute("update f set v = 2 where id = 1")
    # ...A rewrites the table layout...
    ca.execute("alter table f add column extra bigint")
    # ...B's commit must abort at the schema fence
    with pytest.raises(MySQLError) as exc:
        cb.execute("commit")
    assert "schema" in str(exc.value).lower() or \
        "try again" in str(exc.value).lower()
    # and the row kept its pre-txn value
    assert ca.query("select v from f") == [("1",)]


def test_conflicting_writes_across_servers(cluster):
    ca, cb = cluster
    ca.execute("create table c (id bigint primary key, v bigint)")
    ca.execute("insert into c values (1, 0)")
    # sequential increments alternating between servers stay exact
    for i in range(6):
        cli = ca if i % 2 == 0 else cb
        cli.execute("update c set v = v + 1 where id = 1")
    assert ca.query("select v from c") == [("6",)]
    assert cb.query("select v from c") == [("6",)]


def test_sibling_crash_mid_commit_recovers(tmp_path):
    """A server hard-killed AFTER PREWRITE (locks laid down, nothing
    committed) must not wedge the database: the survivor resolves the
    orphaned percolator locks once their TTL expires and rolls the
    transaction BACK (reference: lock_resolver.go; crash point analog
    2pc.go:1027 failpoints). Also exercises torn-WAL tolerance: the
    killed process dies inside the commit path with the shared WAL
    possibly mid-append."""
    procs = []
    try:
        a, pa = _spawn(str(tmp_path))
        procs.append(a)
        ca = MiniClient("127.0.0.1", pa)
        ca.execute("create table r (id bigint primary key, v bigint)")
        ca.execute("insert into r values (1, 1)")
        c, pc = _spawn(str(tmp_path),
                       crash_fp="twopc/after-prewrite")
        procs.append(c)
        cc = MiniClient("127.0.0.1", pc)
        with pytest.raises((MySQLError, ConnectionError, OSError)):
            cc.execute("update r set v = 2 where id = 1")
        c.wait(timeout=30)
        assert c.returncode == 9, "crash server did not die at failpoint"
        # survivor: first read may block on the orphan lock until its
        # TTL (3s) expires; the pre-crash value must win
        t0 = time.time()
        while True:
            try:
                assert ca.query("select v from r") == [("1",)]
                break
            except MySQLError:
                assert time.time() - t0 < 30, "orphan lock never resolved"
                time.sleep(0.5)
        # and the survivor can write through the formerly locked key
        ca.execute("update r set v = 7 where id = 1")
        assert ca.query("select v from r") == [("7",)]
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def test_concurrent_ddl_from_both_servers(cluster):
    """DDL issued from both servers concurrently: the owner-gated job
    queue serializes them; every job lands and both catalogs converge
    (reference: ddl owner election, owner/manager.go; multi-server DDL
    stress is cmd/ddltest's role)."""
    ca, cb = cluster
    errs: list = []

    def mk(cli, names):
        try:
            for nm in names:
                cli.execute(
                    f"create table {nm} (id bigint primary key, v bigint)")
                cli.execute(f"insert into {nm} values (1, 1)")
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    ta = threading.Thread(target=mk, args=(ca, ["ca0", "ca1", "ca2"]))
    tb = threading.Thread(target=mk, args=(cb, ["cb0", "cb1", "cb2"]))
    ta.start()
    tb.start()
    ta.join(timeout=120)
    tb.join(timeout=120)
    assert not errs, errs
    for cli in (ca, cb):
        for nm in ("ca0", "ca1", "ca2", "cb0", "cb1", "cb2"):
            assert cli.query(f"select v from {nm}") == [("1",)]
    # concurrent ALTER from both sides on disjoint tables
    ea: list = []

    def alter(cli, sql):
        try:
            cli.execute(sql)
        except BaseException as e:  # noqa: BLE001
            ea.append(e)

    t1 = threading.Thread(target=alter,
                          args=(ca, "alter table ca0 add column w bigint"))
    t2 = threading.Thread(target=alter,
                          args=(cb, "alter table cb0 add column w bigint"))
    t1.start()
    t2.start()
    t1.join(timeout=120)
    t2.join(timeout=120)
    assert not ea, ea
    got = ca.query("select w from cb0 where id = 1")
    assert got in ([("NULL",)], [(None,)]), got


class _FrozenClock:
    """Stand-in for tso.py's `time` module: the physical clock never
    advances, so EVERY timestamp lands in one millisecond — the exact
    interleaving where the round-4 node-sliced TSO leaked a sibling's
    commit into an open snapshot (bounded staleness)."""

    @staticmethod
    def time() -> float:
        return 1_700_000_000.0


def test_shared_tso_strictly_monotonic_same_millisecond(
        tmp_path, monkeypatch):
    from tidb_tpu.kv import tso as tso_mod

    monkeypatch.setattr(tso_mod, "time", _FrozenClock)
    a = tso_mod.SharedTSO(str(tmp_path))
    b = tso_mod.SharedTSO(str(tmp_path))
    last = 0
    for i in range(4000):
        t = (a if i % 2 else b).next_ts()
        assert t > last, "cross-allocator timestamp went backwards"
        last = t
    a.close()
    b.close()


def test_shared_tso_crash_recovery_floors_above_window(tmp_path):
    from tidb_tpu.kv.tso import SharedTSO

    a = SharedTSO(str(tmp_path))
    issued = [a.next_ts() for _ in range(10)]
    a.close()
    # full-cluster crash where the mmap page never reached disk: the
    # persisted window must still floor the next incarnation
    with open(tmp_path / "tso.mem", "r+b") as f:
        f.write(b"\0" * 8)
    b = SharedTSO(str(tmp_path))
    t = b.next_ts()
    assert t > max(issued), "timestamp repeated after crash"
    b.close()


def test_strict_si_same_millisecond(tmp_path, monkeypatch):
    """A sibling's commit issued AFTER a snapshot opened can never
    surface inside that snapshot, even with the whole schedule packed
    into one physical millisecond. Round 4's node-sliced TSO violated
    exactly this (store/coordinator.py then documented it as a KNOWN
    LIMITATION); the shared allocator closes it. Reference analog: PD
    TSO (oracle/oracles/pd.go:77)."""
    from tidb_tpu.kv import tso as tso_mod
    from tidb_tpu.session import Session
    from tidb_tpu.store.storage import Storage

    monkeypatch.setattr(tso_mod, "time", _FrozenClock)
    s1 = Storage(str(tmp_path), shared=True)
    s2 = Storage(str(tmp_path), shared=True)
    try:
        sess1, sess2 = Session(s1), Session(s2)
        sess1.execute("create table t (id bigint primary key, v bigint)")
        sess1.execute("insert into t values (1, 10)")
        assert sess2.execute("select v from t").rows == [(10,)]
        sess1.execute("begin")
        assert sess1.execute("select v from t").rows == [(10,)]
        # sibling commits under the SAME frozen millisecond
        sess2.execute("update t set v = 99 where id = 1")
        # the open snapshot must keep reading its version...
        assert sess1.execute("select v from t").rows == [(10,)]
        sess1.execute("commit")
        # ...and the next snapshot must see the sibling's commit
        assert sess1.execute("select v from t").rows == [(99,)]
    finally:
        s1.close()
        s2.close()


def test_global_kill_from_sibling(cluster):
    ca, cb = cluster
    conn_id = int(cb.query("select connection_id()")[0][0])
    errs: list = []

    def long_query():
        try:
            cb.query("select sleep(25)")  # interruptible, like MySQL's
        except MySQLError as e:
            errs.append(e)

    t = threading.Thread(target=long_query)
    t.start()
    time.sleep(1.0)
    t0 = time.time()
    ca.execute(f"kill query {conn_id}")
    t.join(timeout=20)
    assert not t.is_alive(), "query was not killed"
    assert time.time() - t0 < 15, "kill took too long"
    assert errs and "interrupt" in str(errs[0]).lower()
    # connection survives a QUERY kill
    assert cb.query("select 1") == [("1",)]
