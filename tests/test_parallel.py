"""Multi-device execution: sharded partial aggregation must match the
single-device path exactly (8 virtual CPU devices, see conftest)."""

import jax
import pytest

from tidb_tpu.bench.tpch import TPCH_Q1, TPCH_Q6, load_lineitem
from tidb_tpu.parallel import DistCopClient, make_mesh
from tidb_tpu.session import Session

N_ROWS = 20_000


@pytest.fixture(scope="module")
def sessions():
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    single = Session()
    load_lineitem(single, N_ROWS)
    dist = Session(single.storage, cop=DistCopClient(make_mesh()))
    return single, dist


class TestShardedAgg:
    def test_q6_matches_single_device(self, sessions):
        single, dist = sessions
        assert dist.query(TPCH_Q6) == single.query(TPCH_Q6)

    def test_q1_matches_single_device(self, sessions):
        single, dist = sessions
        rows_d = dist.query(TPCH_Q1)
        rows_s = single.query(TPCH_Q1)
        assert rows_d == rows_s
        assert len(rows_d) >= 4  # all (flag, status) groups present

    def test_scalar_agg_on_mesh(self, sessions):
        _, dist = sessions
        n = dist.query("select count(*) from lineitem")[0][0]
        assert n == N_ROWS

    def test_mvcc_overlay_on_mesh(self, sessions):
        single, dist = sessions
        dist.execute(
            "insert into lineitem values (999999, 1, 1, 1, 10.00, 1000.00, "
            "0.05, 0.02, 'N', 'O', '1998-01-01', '1998-01-10', '1998-01-20')")
        n = dist.query("select count(*) from lineitem")[0][0]
        assert n == N_ROWS + 1
        assert single.query("select count(*) from lineitem")[0][0] == \
            N_ROWS + 1


def test_dist_fragment_join_agg_device_path(monkeypatch):
    """Join fragments run probe-sharded with replicated build tables under
    the mesh — device path, no host fallback (VERDICT: shard the rest of
    the distributed tier)."""
    import numpy as np

    import tidb_tpu.copr.fragment as F
    from tidb_tpu.parallel import DistCopClient, make_mesh
    from tidb_tpu.session import Session

    def boom(frag, snaps):
        raise AssertionError("host fragment fallback under mesh")
    monkeypatch.setattr(F, "_host_fragment", boom)

    single = Session()
    single.execute("CREATE TABLE d (k INT NOT NULL PRIMARY KEY, "
                   "g VARCHAR(4))")
    single.execute("CREATE TABLE f (id INT NOT NULL PRIMARY KEY, k INT, "
                   "v DECIMAL(8,2))")
    single.execute("INSERT INTO d VALUES (1,'a'),(2,'b'),(3,'a')")
    rows = ",".join(f"({i},{(i % 3) + 1},{i % 40}.50)" for i in range(900))
    single.execute("INSERT INTO f VALUES " + rows)
    safe = single.storage.safe_ts()
    for st in single.storage.tables.values():
        st.compact(safe)

    mesh = make_mesh(jax.devices()[:8])
    dist = Session(single.storage, cop=DistCopClient(mesh))
    q = ("SELECT g, SUM(v), COUNT(*), MIN(v), MAX(v) FROM f, d "
         "WHERE f.k = d.k GROUP BY g ORDER BY g")
    got = dist.query(q)
    monkeypatch.undo()
    want = single.query(q)
    assert got == want


def test_dist_topn_and_rows(monkeypatch):
    import tidb_tpu.copr.fragment as F  # noqa: F401
    from tidb_tpu.parallel import DistCopClient, make_mesh
    from tidb_tpu.session import Session

    single = Session()
    single.execute("CREATE TABLE s (a INT NOT NULL PRIMARY KEY, b INT)")
    rows = ",".join(f"({i},{(i * 37) % 1000})" for i in range(2000))
    single.execute("INSERT INTO s VALUES " + rows)
    safe = single.storage.safe_ts()
    for st in single.storage.tables.values():
        st.compact(safe)
    mesh = make_mesh(jax.devices()[:8])
    dist = Session(single.storage, cop=DistCopClient(mesh))
    for q in ("SELECT a, b FROM s ORDER BY b DESC, a LIMIT 9",
              "SELECT a FROM s WHERE b < 50 ORDER BY a"):
        assert dist.query(q) == single.query(q), q
