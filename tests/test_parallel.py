"""Multi-device execution: sharded partial aggregation must match the
single-device path exactly (8 virtual CPU devices, see conftest)."""

import jax
import pytest

from tidb_tpu.bench.tpch import TPCH_Q1, TPCH_Q6, load_lineitem
from tidb_tpu.parallel import DistCopClient, make_mesh
from tidb_tpu.session import Session

N_ROWS = 20_000


@pytest.fixture(scope="module")
def sessions():
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    single = Session()
    load_lineitem(single, N_ROWS)
    dist = Session(single.storage, cop=DistCopClient(make_mesh()))
    return single, dist


class TestShardedAgg:
    def test_q6_matches_single_device(self, sessions):
        single, dist = sessions
        assert dist.query(TPCH_Q6) == single.query(TPCH_Q6)

    def test_q1_matches_single_device(self, sessions):
        single, dist = sessions
        rows_d = dist.query(TPCH_Q1)
        rows_s = single.query(TPCH_Q1)
        assert rows_d == rows_s
        assert len(rows_d) >= 4  # all (flag, status) groups present

    def test_scalar_agg_on_mesh(self, sessions):
        _, dist = sessions
        n = dist.query("select count(*) from lineitem")[0][0]
        assert n == N_ROWS

    def test_mvcc_overlay_on_mesh(self, sessions):
        single, dist = sessions
        dist.execute(
            "insert into lineitem values (999999, 1, 1, 1, 10.00, 1000.00, "
            "0.05, 0.02, 'N', 'O', '1998-01-01', '1998-01-10', '1998-01-20')")
        n = dist.query("select count(*) from lineitem")[0][0]
        assert n == N_ROWS + 1
        assert single.query("select count(*) from lineitem")[0][0] == \
            N_ROWS + 1
