"""TPC-H Q1/Q6 correctness: engine results vs an independent numpy oracle.

The differential-testing strategy SURVEY.md §7 prescribes: same generated
data, two independent computations, identical digests required.
"""

import numpy as np
import pytest

from tidb_tpu.bench.tpch import (
    TPCH_Q1,
    TPCH_Q6,
    generate_lineitem_arrays,
    load_lineitem,
)
from tidb_tpu.session import Session
from tidb_tpu.types import Decimal
from tidb_tpu.types.value import parse_date

N_ROWS = 60_000


@pytest.fixture(scope="module")
def loaded():
    s = Session()
    load_lineitem(s, N_ROWS)
    arrays = generate_lineitem_arrays(N_ROWS)
    return s, arrays


class TestQ6:
    def test_q6_digest(self, loaded):
        s, a = loaded
        rows = s.query(TPCH_Q6)
        assert len(rows) == 1
        got = rows[0][0]

        d1 = parse_date("1994-01-01")
        d2 = parse_date("1995-01-01")
        mask = (
            (a["l_shipdate"] >= d1)
            & (a["l_shipdate"] < d2)
            & (a["l_discount"] >= 5)
            & (a["l_discount"] <= 7)
            & (a["l_quantity"] < 2400)
        )
        # extendedprice(s2) * discount(s2) -> scale 4
        oracle = int(np.sum(a["l_extendedprice"][mask].astype(object)
                            * a["l_discount"][mask].astype(object)))
        assert isinstance(got, Decimal)
        assert got.unscaled == oracle and got.scale == 4

    def test_q6_selectivity_sane(self, loaded):
        s, a = loaded
        # ~ 1/7 of dates x 3/11 discounts x 23/50 qty ≈ 1.7% selectivity
        n = s.query(
            "select count(*) from lineitem where l_shipdate >= "
            "date '1994-01-01' and l_shipdate < date '1995-01-01' and "
            "l_discount between 0.05 and 0.07 and l_quantity < 24"
        )[0][0]
        assert 0.005 * N_ROWS < n < 0.04 * N_ROWS


class TestQ1:
    def test_q1_digest(self, loaded):
        s, a = loaded
        rows = s.query(TPCH_Q1)

        cutoff = parse_date("1998-12-01") - 90
        mask = a["l_shipdate"] <= cutoff
        rf = a["l_returnflag"][mask]
        ls = a["l_linestatus"][mask]
        qty = a["l_quantity"][mask].astype(object)
        price = a["l_extendedprice"][mask].astype(object)
        disc = a["l_discount"][mask].astype(object)
        tax = a["l_tax"][mask].astype(object)

        flag_names = np.array(["A", "R", "N"])
        status_names = np.array(["F", "O"])
        oracle = {}
        for rfc in range(3):
            for lsc in range(2):
                g = (rf == rfc) & (ls == lsc)
                cnt = int(g.sum())
                if cnt == 0:
                    continue
                sum_qty = int(qty[g].sum())
                sum_price = int(price[g].sum())
                # disc_price scale 4: price * (1 - disc) = price*(100-disc)
                sum_disc_price = int((price[g] * (100 - disc[g])).sum())
                # charge scale 6: price*(100-disc)*(100+tax)
                sum_charge = int(
                    (price[g] * (100 - disc[g]) * (100 + tax[g])).sum())
                avg_qty = Decimal(sum_qty, 2).div(Decimal.from_int(cnt))
                avg_price = Decimal(sum_price, 2).div(Decimal.from_int(cnt))
                avg_disc = Decimal(int(disc[g].sum()), 2).div(
                    Decimal.from_int(cnt))
                oracle[(flag_names[rfc], status_names[lsc])] = (
                    Decimal(sum_qty, 2), Decimal(sum_price, 2),
                    Decimal(sum_disc_price, 4), Decimal(sum_charge, 6),
                    avg_qty, avg_price, avg_disc, cnt,
                )

        assert len(rows) == len(oracle)
        # engine rows are ordered by returnflag, linestatus (A<N<R binary)
        got_keys = [(r[0], r[1]) for r in rows]
        assert got_keys == sorted(oracle.keys())
        for r in rows:
            key = (r[0], r[1])
            want = oracle[key]
            got = tuple(r[2:])
            assert got == want, f"group {key}:\n got {got}\nwant {want}"

    def test_q1_plan_is_pushed(self, loaded):
        s, _ = loaded
        lines = "\n".join(r[0] for r in s.query("explain " + TPCH_Q1))
        assert "TableRead[TiTPU]" in lines
        assert "agg(groups=2" in lines
