import pytest

from tidb_tpu.sql import ParseError, parse_sql
from tidb_tpu.sql import ast
from tidb_tpu.types import Decimal
from tidb_tpu.types.field_type import TypeKind

TPCH_Q6 = """
select sum(l_extendedprice * l_discount) as revenue
from lineitem
where l_shipdate >= date '1994-01-01'
  and l_shipdate < date '1994-01-01' + interval '1' year
  and l_discount between 0.05 and 0.07
  and l_quantity < 24;
"""

TPCH_Q1 = """
select
    l_returnflag, l_linestatus,
    sum(l_quantity) as sum_qty,
    sum(l_extendedprice) as sum_base_price,
    sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
    sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
    avg(l_quantity) as avg_qty,
    avg(l_extendedprice) as avg_price,
    avg(l_discount) as avg_disc,
    count(*) as count_order
from lineitem
where l_shipdate <= date '1998-12-01' - interval '90' day
group by l_returnflag, l_linestatus
order by l_returnflag, l_linestatus;
"""


def one(sql):
    stmts = parse_sql(sql)
    assert len(stmts) == 1
    return stmts[0]


class TestSelect:
    def test_q6_shape(self):
        s = one(TPCH_Q6)
        assert isinstance(s, ast.SelectStmt)
        assert len(s.fields) == 1
        f = s.fields[0]
        assert f.alias == "revenue"
        assert isinstance(f.expr, ast.FuncCall) and f.expr.name == "SUM"
        # where is an AND chain with a BETWEEN inside
        found_between = []

        def walk(e):
            if isinstance(e, ast.Between):
                found_between.append(e)
            for attr in ("left", "right", "operand", "low", "high"):
                sub = getattr(e, attr, None)
                if isinstance(sub, ast.Expr):
                    walk(sub)

        walk(s.where)
        assert len(found_between) == 1
        b = found_between[0]
        assert b.low == ast.Literal(Decimal.parse("0.05"), "decimal")

    def test_q1_shape(self):
        s = one(TPCH_Q1)
        assert len(s.fields) == 10
        assert len(s.group_by) == 2
        assert len(s.order_by) == 2
        assert s.fields[-1].expr.is_star
        # date literal minus interval
        assert isinstance(s.where, ast.BinaryOp)
        assert isinstance(s.where.right, ast.BinaryOp)
        assert isinstance(s.where.right.right, ast.IntervalExpr)
        assert s.where.right.right.unit == "DAY"

    def test_precedence(self):
        s = one("select 1 + 2 * 3")
        e = s.fields[0].expr
        assert e.op == "+" and e.right.op == "*"

    def test_and_or_precedence(self):
        s = one("select * from t where a = 1 or b = 2 and c = 3")
        w = s.where
        assert w.op == "OR" and w.right.op == "AND"

    def test_in_list_and_not_in(self):
        s = one("select * from t where a in (1, 2) and b not in ('x')")
        w = s.where
        assert isinstance(w.left, ast.InList) and not w.left.negated
        assert isinstance(w.right, ast.InList) and w.right.negated

    def test_is_null(self):
        s = one("select * from t where a is null and b is not null")
        assert isinstance(s.where.left, ast.IsNull)
        assert s.where.right.negated

    def test_like(self):
        s = one("select * from t where name like 'a%' and x not like '_b'")
        assert isinstance(s.where.left, ast.Like)
        assert s.where.right.negated

    def test_joins(self):
        s = one(
            "select * from a join b on a.id = b.id "
            "left join c as cc on b.k = cc.k"
        )
        j = s.from_
        assert isinstance(j, ast.Join) and j.kind == "LEFT"
        assert j.right.alias == "cc"
        assert j.left.kind == "INNER"

    def test_comma_join(self):
        s = one("select * from a, b where a.x = b.x")
        assert isinstance(s.from_, ast.Join) and s.from_.kind == "CROSS"

    def test_limit_offset_forms(self):
        assert one("select * from t limit 5").limit == 5
        s = one("select * from t limit 10 offset 20")
        assert (s.limit, s.offset) == (10, 20)
        s2 = one("select * from t limit 20, 10")
        assert (s2.limit, s2.offset) == (10, 20)

    def test_group_having_order(self):
        s = one(
            "select a, count(*) from t group by a having count(*) > 1 "
            "order by 2 desc, a"
        )
        assert s.having is not None
        assert s.order_by[0].desc and not s.order_by[1].desc

    def test_case_cast(self):
        s = one(
            "select case when a > 0 then 'pos' else 'neg' end, "
            "cast(a as decimal(10,2)) from t"
        )
        assert isinstance(s.fields[0].expr, ast.Case)
        c = s.fields[1].expr
        assert isinstance(c, ast.Cast)
        assert c.target.kind == TypeKind.DECIMAL and c.target.scale == 2

    def test_subqueries(self):
        s = one("select * from t where a in (select b from u) and "
                "exists (select 1 from v)")
        assert isinstance(s.where.left, ast.InSubquery)
        assert isinstance(s.where.right, ast.SubqueryExpr)
        assert s.where.right.exists

    def test_derived_table(self):
        s = one("select x from (select a as x from t) sub")
        assert isinstance(s.from_, ast.SubqueryTable)
        assert s.from_.alias == "sub"

    def test_distinct_and_wildcards(self):
        s = one("select distinct t.*, a from t")
        assert s.distinct
        assert s.fields[0].wildcard_table == "t"

    def test_quoted_ident_and_comments(self):
        s = one("select `select` from t -- trailing\n where /* c */ x = 1")
        assert s.fields[0].expr.name == "select"


class TestDMLDDL:
    def test_insert_forms(self):
        s = one("insert into t (a, b) values (1, 'x'), (2, 'y')")
        assert s.columns == ["a", "b"] and len(s.rows) == 2
        s2 = one("insert into t values (1)")
        assert s2.columns is None
        s3 = one("insert into t select * from u")
        assert s3.select is not None

    def test_update_delete(self):
        s = one("update t set a = a + 1, b = 'x' where id = 3")
        assert len(s.assignments) == 2
        d = one("delete from t where a < 0")
        assert d.where is not None

    def test_create_table(self):
        s = one(
            "create table if not exists t ("
            "id bigint primary key auto_increment, "
            "name varchar(20) not null default 'n', "
            "price decimal(10, 2), "
            "created date, "
            "key idx_name (name), "
            "unique key uq (price, created))"
        )
        assert s.if_not_exists
        assert len(s.columns) == 4 and len(s.indices) == 2
        assert s.columns[0].primary_key and s.columns[0].auto_increment
        assert s.columns[1].not_null
        assert s.indices[1].unique

    def test_create_drop_database(self):
        assert one("create database if not exists db1").if_not_exists
        assert one("drop database db1").name == "db1"

    def test_drop_table_multi(self):
        s = one("drop table if exists a, b")
        assert s.if_exists and len(s.tables) == 2

    def test_txn_stmts(self):
        kinds = [type(s).__name__ for s in parse_sql(
            "begin; commit; start transaction; rollback;"
        )]
        assert kinds == ["BeginStmt", "CommitStmt", "BeginStmt", "RollbackStmt"]

    def test_explain_show_use(self):
        e = one("explain select * from t")
        assert isinstance(e.target, ast.SelectStmt)
        assert one("show tables").kind == "TABLES"
        assert one("show create table t").target.name == "t"
        assert one("use mydb").db == "mydb"

    def test_decimal_precision_rejected(self):
        with pytest.raises(ParseError):
            one("create table t (a decimal(30, 5))")

    def test_error_position(self):
        with pytest.raises(ParseError) as ei:
            one("select from t")
        assert "near" in str(ei.value)

    def test_multi_statement(self):
        stmts = parse_sql("select 1; select 2;")
        assert len(stmts) == 2
