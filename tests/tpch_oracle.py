"""SQLite differential oracle for the TPC-H suite.

The reference validates plans/results against a golden TPC-H corpus
(reference: cmd/explaintest/t/tpch.test, r/tpch.result). We go one better:
load the *same generated rows* into sqlite3 (stdlib) and compare actual
query results, value by value, after normalization. Decimals become floats
in sqlite, so numeric cells compare under tolerance; dates normalize to
ISO strings.
"""

from __future__ import annotations

import datetime as _dt
import re
import sqlite3
from typing import Any

import numpy as np


def load_sqlite(data: dict[str, dict[str, Any]],
                schemas: dict[str, str]) -> sqlite3.Connection:
    conn = sqlite3.connect(":memory:")
    for table, cols in data.items():
        decoded = {}
        for name, v in cols.items():
            if isinstance(v, tuple):
                vocab, codes = v
                arr = np.array(vocab, dtype=object)[codes]
            else:
                arr = np.asarray(v)
            decoded[name] = arr
        names = list(decoded)
        ddl_cols = _sqlite_cols(schemas[table], names)
        conn.execute(f"create table {table} ({', '.join(ddl_cols)})")
        rows = _to_python_rows(table, names, decoded, schemas[table])
        ph = ", ".join("?" * len(names))
        conn.executemany(f"insert into {table} values ({ph})", rows)
    conn.commit()
    return conn


def _sqlite_cols(ddl: str, names: list[str]) -> list[str]:
    out = []
    for n in names:
        m = re.search(rf"\b{n}\s+(\w+)", ddl)
        t = m.group(1).lower() if m else "text"
        if t in ("bigint", "int", "integer"):
            out.append(f"{n} integer")
        elif t == "decimal":
            out.append(f"{n} real")
        else:
            out.append(f"{n} text")
    return out


def _to_python_rows(table: str, names: list[str],
                    decoded: dict[str, np.ndarray], ddl: str):
    cols = []
    for n in names:
        arr = decoded[n]
        m = re.search(rf"\b{n}\s+(\w+)", ddl)
        t = m.group(1).lower() if m else "text"
        if t == "decimal":
            cols.append([v / 100.0 for v in arr.tolist()])
        elif t == "date":
            epoch = _dt.date(1970, 1, 1)  # matches types.value.encode_date
            cols.append([str(epoch + _dt.timedelta(days=int(v)))
                         for v in arr.tolist()])
        else:
            cols.append(arr.tolist())
    return list(zip(*cols))


def to_sqlite_sql(sql: str) -> str:
    """Rewrite our MySQL-flavored TPC-H text into sqlite dialect."""
    s = sql
    s = re.sub(
        r"date\s+'([0-9-]+)'\s*([+-])\s*interval\s+'(\d+)'\s+(\w+)",
        lambda m: f"date('{m.group(1)}', '{m.group(2)}{m.group(3)} "
                  f"{m.group(4)}')",
        s, flags=re.IGNORECASE)
    s = re.sub(r"date\s+'([0-9-]+)'", r"'\1'", s, flags=re.IGNORECASE)
    s = re.sub(r"extract\s*\(\s*year\s+from\s+([a-z0-9_.]+)\s*\)",
               r"cast(strftime('%Y', \1) as integer)", s,
               flags=re.IGNORECASE)
    s = re.sub(r"substring\s*\(\s*([a-z0-9_.]+)\s+from\s+(\d+)\s+for"
               r"\s+(\d+)\s*\)",
               r"substr(\1, \2, \3)", s, flags=re.IGNORECASE)
    return s


def normalize_cell(v: Any) -> Any:
    if v is None:
        return None
    if isinstance(v, _dt.date):
        return v.isoformat()
    if isinstance(v, bool):
        return int(v)
    if isinstance(v, (int, np.integer)):
        return float(v)
    if isinstance(v, (float, np.floating)):
        return float(v)
    s = str(v)
    try:
        return float(s)
    except ValueError:
        return s


def rows_equal(got: list[tuple], want: list[tuple],
               ordered: bool, tol: float = 5e-5) -> tuple[bool, str]:
    g = [tuple(normalize_cell(c) for c in r) for r in got]
    w = [tuple(normalize_cell(c) for c in r) for r in want]
    if len(g) != len(w):
        return False, f"row count {len(g)} != {len(w)}"
    if not ordered:
        g = sorted(g, key=_sort_key)
        w = sorted(w, key=_sort_key)
    for i, (gr, wr) in enumerate(zip(g, w)):
        if len(gr) != len(wr):
            return False, f"row {i} arity {len(gr)} != {len(wr)}"
        for j, (a, b) in enumerate(zip(gr, wr)):
            if not _cell_eq(a, b, tol):
                return False, (f"row {i} col {j}: {a!r} != {b!r}\n"
                               f" got row: {gr}\nwant row: {wr}")
    return True, ""


def _cell_eq(a: Any, b: Any, tol: float) -> bool:
    if a is None or b is None:
        return a is None and b is None
    if isinstance(a, float) and isinstance(b, float):
        return abs(a - b) <= max(tol, tol * max(abs(a), abs(b)))
    return a == b


def _sort_key(row: tuple) -> tuple:
    return tuple((0, v) if isinstance(v, float) else (1, str(v))
                 for v in row)
