"""Online range splits: the crash-safe split protocol and the
heat-driven auto-split actuator, fast and in-process.

Covers: split_spec/table_gaps table algebra, the leader-coordinated
split_range protocol (journal -> meta commit -> WAL partition -> ready
-> parent retire), deterministic recovery at each in-process failpoint
(roll-back before the meta commit, roll-forward after), the router
under back-to-back split storms (typed EpochNotMatch retries only —
zero failed statements), heat-plane cell migration on split, the
advisory -> auto-split acting loop end-to-end, the [ranges] auto-split
zero-work/poison contract, the range-split-flap inspection rule, and
the knob plumbing (parse/validate/seed/hot-reload + /status).

The kill-9 chaos suite over real child processes lives in
tests/test_split_chaos.py (slow-marked).
"""

from __future__ import annotations

import threading
import time

import pytest

from tidb_tpu import obs
from tidb_tpu.kv.mvcc import OP_PUT, Mutation
from tidb_tpu.kv.rangeclient import RangeRouter
from tidb_tpu.kv.rangemeta import (RangeSpec, split_keyspace, split_spec,
                                   table_gaps)
from tidb_tpu.kv.tso import TimestampOracle
from tidb_tpu.kv.twopc import Snapshot, TwoPhaseCommitter
from tidb_tpu.obs_heat import RangeHeatRecorder
from tidb_tpu.rpc.errors import RPCError
from tidb_tpu.rpc.ranged import RangeDirectory, RangeServer
from tidb_tpu.util import failpoint


@pytest.fixture(autouse=True)
def _clean_failpoints():
    yield
    failpoint.disable_all()


def _server(tmp_path, count=2, lease_ms=60_000, **kw):
    return RangeServer(str(tmp_path), lease_ms=lease_ms,
                       specs=split_keyspace(count), **kw)


def _commit(committer, pairs: dict, tso) -> int:
    muts = [Mutation(OP_PUT, k, v) for k, v in sorted(pairs.items())]
    return committer.commit(muts, tso.ts())


def _seed(tmp_path, srv, n=30):
    """n single-key rows through the real 2PC path; returns the oracle
    dict and the (router, committer, tso) triple."""
    tso = TimestampOracle()
    router = RangeRouter(root=str(tmp_path))
    committer = TwoPhaseCommitter(router, tso)
    oracle = {}
    for i in range(n):
        k = b"k%04d" % i
        v = b"v%04d" % i
        _commit(committer, {k: v}, tso)
        oracle[k] = v
    return oracle, router, committer, tso


# ==================== table algebra ====================

def test_split_spec_delta_and_validation():
    parent = RangeSpec(1, b"a", b"z", epoch=3)
    left, right = split_spec(parent, b"m", 7)
    assert (left.id, left.start_key, left.end_key, left.epoch) == \
        (1, b"a", b"m", 4)
    assert (right.id, right.start_key, right.end_key, right.epoch) == \
        (7, b"m", b"z", 4)
    # the split key must fall strictly inside the parent
    for bad in (b"a", b"z", b"", b"zz"):
        with pytest.raises(ValueError):
            split_spec(parent, bad, 7)
    with pytest.raises(ValueError):
        split_spec(parent, b"m", 1)  # child id collides with parent
    # an unbounded parent splits fine
    left, right = split_spec(RangeSpec(2, b"m", b""), b"q", 9)
    assert right.end_key == b""


def test_table_gaps_detects_every_defect():
    ok = split_keyspace(4)
    assert table_gaps(ok) == []
    assert table_gaps([]) == ["empty table"]
    # gap
    bad = [RangeSpec(1, b"", b"a"), RangeSpec(2, b"b", b"")]
    assert any("gap" in d for d in table_gaps(bad))
    # overlap
    bad = [RangeSpec(1, b"", b"c"), RangeSpec(2, b"b", b"")]
    assert any("overlap" in d for d in table_gaps(bad))
    # missing edges
    bad = [RangeSpec(1, b"a", b"")]
    assert any("-inf" in d for d in table_gaps(bad))
    bad = [RangeSpec(1, b"", b"x")]
    assert any("+inf" in d for d in table_gaps(bad))
    # duplicate ids
    bad = [RangeSpec(1, b"", b"m"), RangeSpec(1, b"m", b"")]
    assert any("duplicate" in d for d in table_gaps(bad))


# ==================== split mechanics ====================

def test_split_range_partitions_table_and_data(tmp_path):
    srv = _server(tmp_path)
    try:
        oracle, router, committer, tso = _seed(tmp_path, srv)
        left, right = srv.split_range(1, b"k0010")
        # table: three ranges, gap-free, children at epoch parent+1
        specs = srv.directory.load_specs()
        assert table_gaps(specs) == []
        assert len(specs) == 3
        assert (left.id, left.epoch) == (1, 2)
        assert right.epoch == 2 and right.id == 3
        # both children led here immediately (no lease-tick wait)
        assert sorted(srv.hosted_ids()) == [1, 2, 3]
        # the parent physically retired the child's half...
        with srv._mu:
            l_parent = srv._leaders[1]
            l_child = srv._leaders[3]
        assert l_parent.store.export_range(b"k0010", b"\x80") == []
        # ...and the child holds exactly it
        assert l_child.store.export_range(b"", b"k0010") == []
        assert l_child.store.get(b"k0015", tso.ts()) == b"v0015"
        # no journal left behind
        assert srv.directory.read_split(1) is None
        # every acked write present exactly once through the router
        snap = Snapshot(router, tso, tso.ts())
        assert dict(snap.scan(b"", b"", -1)) == oracle
        # both children keep accepting writes
        _commit(committer, {b"k0005x": b"l", b"k0020x": b"r"}, tso)
        snap = Snapshot(router, tso, tso.ts())
        assert snap.get(b"k0005x") == b"l"
        assert snap.get(b"k0020x") == b"r"
        # the metric moved with trigger=manual
        assert 'tidb_range_splits_total{trigger="manual"}' \
            in obs.PROCESS_METRICS.render()
    finally:
        srv.close()


def test_split_rejects_bad_requests(tmp_path):
    srv = _server(tmp_path)
    try:
        # split key outside the parent's bounds
        with pytest.raises(RPCError):
            srv.split_range(1, b"\x81")
        # unknown / un-led range
        with pytest.raises(Exception):
            srv.split_range(99, b"k")
        # a range already splitting refuses a second split
        srv.directory.begin_split(1, b"\x10")
        with pytest.raises(RPCError, match="already splitting"):
            srv.directory.begin_split(1, b"\x20")
    finally:
        srv.close()


def test_split_exception_before_meta_commit_rolls_back(tmp_path):
    """An in-process failure BEFORE the meta rename leaves no trace:
    the journal is withdrawn, the table keeps its pre-split shape, and
    serving continues — the same decision the kill-9 successor takes."""
    srv = _server(tmp_path)
    try:
        oracle, router, committer, tso = _seed(tmp_path, srv, n=10)
        failpoint.enable("range/split-before-meta-commit", RuntimeError)
        with pytest.raises(RuntimeError):
            srv.split_range(1, b"k0005")
        failpoint.disable("range/split-before-meta-commit")
        assert failpoint.hits("range/split-before-meta-commit") == 1
        specs = srv.directory.load_specs()
        assert len(specs) == 2 and table_gaps(specs) == []
        assert srv.directory.read_split(1) is None
        snap = Snapshot(router, tso, tso.ts())
        assert dict(snap.scan(b"", b"", -1)) == oracle
        # and a later split of the same range succeeds
        srv.split_range(1, b"k0005")
        assert len(srv.directory.load_specs()) == 3
    finally:
        srv.close()


@pytest.mark.parametrize("point", [
    "range/split-after-meta-commit",
    "range/split-mid-wal-partition",
    "range/split-before-parent-retire",
])
def test_split_exception_after_meta_commit_rolls_forward(
        tmp_path, point):
    """Past the meta rename the split is committed: an in-process
    failure at any later stage leaves a pending/ready journal that the
    next lease tick's recovery completes — half-committed splits heal
    without manual intervention, and acked data survives intact."""
    srv = _server(tmp_path)
    try:
        oracle, router, committer, tso = _seed(tmp_path, srv, n=20)
        failpoint.enable(point, RuntimeError)
        with pytest.raises(RuntimeError):
            srv.split_range(1, b"k0010")
        failpoint.disable(point)
        # committed but unfinished: the journal survives the failure
        assert srv.directory.read_split(1) is not None
        assert len(srv.directory.load_specs()) == 3
        # recovery runs on the lease tick (the chaos suite exercises
        # the same path on a fresh process)
        srv._lease_tick()
        assert srv.directory.read_split(1) is None
        assert sorted(srv.hosted_ids()) == [1, 2, 3]
        assert table_gaps(srv.directory.load_specs()) == []
        snap = Snapshot(router, tso, tso.ts())
        assert dict(snap.scan(b"", b"", -1)) == oracle
        _commit(committer, {b"k0005y": b"l", b"k0015y": b"r"}, tso)
        snap = Snapshot(router, tso, tso.ts())
        assert snap.get(b"k0005y") == b"l"
        assert snap.get(b"k0015y") == b"r"
    finally:
        srv.close()


def test_pending_child_lease_embargo(tmp_path):
    """A mid-split child (journal pending) must not be acquirable: its
    data dir may be partial. Only the parent-side recovery lifts the
    embargo by completing the split."""
    srv = _server(tmp_path)
    try:
        _seed(tmp_path, srv, n=10)
        failpoint.enable("range/split-mid-wal-partition", RuntimeError)
        with pytest.raises(RuntimeError):
            srv.split_range(1, b"k0005")
        failpoint.disable("range/split-mid-wal-partition")
        assert srv.directory.pending_children() == {3}
        # a second server joining now must NOT lease the pending child
        srv2 = RangeServer(str(tmp_path), lease_ms=60_000)
        try:
            assert 3 not in srv2.hosted_ids()
        finally:
            srv2.close()
    finally:
        srv.close()


# ==================== router under a split storm ====================

def test_router_sees_only_typed_retries_during_split_storm(tmp_path):
    """Concurrent RangeRouter clients through back-to-back splits:
    every statement lands exactly once inside the Backoffer budget —
    zero failed statements, zero stale-route writes, the EpochNotMatch
    -> reload -> retry loop proven under real concurrency."""
    srv = _server(tmp_path)
    failures: list = []
    written: dict[bytes, bytes] = {}
    stop = threading.Event()
    tso = TimestampOracle()

    def writer(wid: int):
        router = RangeRouter(root=str(tmp_path))
        committer = TwoPhaseCommitter(router, tso)
        i = 0
        while not stop.is_set():
            k = b"w%d-%04d" % (wid, i)
            try:
                _commit(committer, {k: b"v%d" % wid}, tso)
                written[k] = b"v%d" % wid
            except Exception as e:  # noqa: BLE001 — any failure flunks
                failures.append((k, repr(e)))
                return
            i += 1

    threads = [threading.Thread(target=writer, args=(w,))
               for w in range(3)]
    for t in threads:
        t.start()
    try:
        # back-to-back splits while the writers hammer: w0* and w1*
        # straddle each split point
        time.sleep(0.2)
        srv.split_range(1, b"w0-")
        time.sleep(0.2)
        srv.split_range(3, b"w1-")
        time.sleep(0.2)
        srv.split_range(4, b"w2-")
        time.sleep(0.3)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
    try:
        assert failures == []
        assert all(not t.is_alive() for t in threads)
        specs = srv.directory.load_specs()
        assert table_gaps(specs) == [] and len(specs) == 5
        assert len(written) > 30, "writers barely ran"
        # exactly-once: the store agrees with every acked write
        router = RangeRouter(root=str(tmp_path))
        snap = Snapshot(router, tso, tso.ts())
        rows = dict(snap.scan(b"", b"", -1))
        assert rows == written
    finally:
        srv.close()


# ==================== heat-plane cell migration ====================

def _hot_recorder(events=None):
    rec = RangeHeatRecorder(events=events)
    rec.configure(enabled=True, bucket_seconds=1, sustained_buckets=1,
                  hot_ratio=1.5, key_sample_cap=64)
    return rec


def test_heat_on_split_retires_parent_cells(tmp_path):
    """After a split the recorder must show NO phantom parent state:
    totals/samples/streaks and every ring bucket's parent cells are
    dropped, and both children inherit a clean window."""
    rec = _hot_recorder()
    specs = split_keyspace(2)
    rec.set_specs(specs)
    for i in range(50):
        rec.note_range(1, write_rows=1, write_bytes=32,
                       keys=[b"h%03d" % i])
    assert rec.range_totals(1)[2] == 50
    assert rec.split_advisory(1) is not None
    post = [RangeSpec(1, b"", b"h025", 2), RangeSpec(3, b"h025",
                                                     b"\x80", 2),
            RangeSpec(2, b"\x80", b"", 1)]
    rec.on_split(1, post)
    # the parent id (now the LEFT child) starts clean — its recorded
    # cells spanned the pre-split bounds
    assert rec.range_totals(1) == (0, 0, 0, 0)
    assert rec.split_advisory(1) is None
    with rec._mu:
        assert all(1 not in b["cells"] and 3 not in b["cells"]
                   for b in rec._ring)
        assert [s.id for s in rec._specs] == [1, 3, 2]
    # no findings name a phantom range
    assert all(f["item"] != "r1" for f in rec.findings())
    # fresh traffic on the children accounts normally
    rec.note_range(3, write_rows=2, write_bytes=8, keys=[b"h030"])
    assert rec.range_totals(3)[2] == 2


def test_split_server_migrates_heat_cells(tmp_path):
    """The server wires on_split into split_range: leader-applied
    traffic recorded pre-split never haunts the post-split table."""
    rec = _hot_recorder()
    srv = _server(tmp_path, heat=rec)
    rec.set_specs(srv.specs)
    try:
        oracle, router, committer, tso = _seed(tmp_path, srv, n=20)
        assert rec.range_totals(1)[2] > 0
        srv.split_range(1, b"k0010")
        assert rec.range_totals(1) == (0, 0, 0, 0)
        with rec._mu:
            assert [s.id for s in rec._specs] == [1, 3, 2]
        # post-split traffic lands on the children's own cells
        _commit(committer, {b"k0001z": b"v"}, tso)
        _commit(committer, {b"k0015z": b"v"}, tso)
        assert rec.range_totals(1)[2] == 1
        assert rec.range_totals(3)[2] == 1
    finally:
        srv.close()


# ==================== the acting loop ====================

def test_auto_split_acting_loop_end_to_end(tmp_path):
    """ISSUE 19's closed loop, no manual intervention: skewed writes on
    a real multi-range store -> heat advisory -> auto-split at the
    advised weighted-median key -> range_split event with trigger=auto
    -> both children independently leased and serving."""
    events = obs.EventLog()
    rec = _hot_recorder(events=events)
    srv = _server(tmp_path, lease_ms=200, events=events, heat=rec,
                  auto_split=True, split_cooldown_ms=0)
    rec.set_specs(srv.specs)
    try:
        tso = TimestampOracle()
        router = RangeRouter(root=str(tmp_path))
        committer = TwoPhaseCommitter(router, tso)
        written = {}
        deadline = time.monotonic() + 30.0
        i = 0
        while time.monotonic() < deadline \
                and len(srv.directory.load_specs()) < 3:
            k = b"hot%04d" % (i % 64)
            _commit(committer, {k: b"x" * 32}, tso)
            written[k] = b"x" * 32
            i += 1
        specs = srv.directory.load_specs()
        assert len(specs) == 3, "the actuator never fired"
        assert table_gaps(specs) == []
        # the tick thread bumps the counter just after the split lands
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and srv._auto_splits == 0:
            time.sleep(0.02)
        assert srv._auto_splits == 1
        # the structured event: parent, children, epoch, advised key
        # digest, trigger
        evs = [e for e in events.snapshot()
               if e["kind"] == "range_split"]
        assert len(evs) == 1
        d = evs[0]["detail"]
        assert d.startswith("r1 -> r1+r3 at ")
        assert "trigger=auto" in d and "advisory=" in d
        assert "epoch=2" in d
        # the metric moved with trigger=auto
        assert 'tidb_range_splits_total{trigger="auto"}' \
            in obs.PROCESS_METRICS.render()
        # both children leased here and serving: write to each side
        assert sorted(srv.hosted_ids()) == [1, 2, 3]
        _commit(committer, {b"hot0000z": b"l", b"hoz": b"r"}, tso)
        snap = Snapshot(router, tso, tso.ts())
        assert dict(snap.scan(b"hot", b"hp", -1)) == \
            written | {b"hot0000z": b"l", b"hoz": b"r"}
    finally:
        srv.close()


def test_auto_split_cooldown_and_cap(tmp_path):
    srv = _server(tmp_path, auto_split=True, split_cooldown_ms=3_600_000,
                  max_auto_splits=4, heat=_hot_recorder())
    try:
        # a recent auto-split inside the cooldown: the tick does nothing
        srv._last_auto_split_ms = time.time() * 1000.0
        srv.heat.findings = lambda: (_ for _ in ()).throw(
            AssertionError("tick consulted findings inside cooldown"))
        srv._auto_split_tick()
        # the lifetime cap stops the actuator even with cooldown clear
        srv._last_auto_split_ms = 0.0
        srv._auto_splits = srv.max_auto_splits
        srv._auto_split_tick()
    finally:
        srv.close()


def test_auto_split_disabled_is_zero_work(tmp_path):
    """The [ranges] auto-split=false default does NO actuator work —
    poison-pinned like the heatmap contract: every surface the actuator
    would touch raises, and the lease tick still runs clean. Splits
    never occur spontaneously."""
    rec = _hot_recorder()
    srv = _server(tmp_path, heat=rec, auto_split=False)
    try:
        _seed(tmp_path, srv, n=10)

        def _poison(*a, **k):
            raise AssertionError("actuator worked while disabled")

        rec.findings = _poison
        rec.split_advisory = _poison
        srv.split_range = _poison
        for _ in range(3):
            srv._lease_tick()
        assert len(srv.directory.load_specs()) == 2
        # flipping the knob on is what arms the tick (hot reload path)
        assert srv.auto_split is False
    finally:
        srv.close()


def test_split_failpoint_declared_and_auto_site_fires(tmp_path):
    """Every range/split-* + actuator failpoint is DECLARED, and the
    actuator's own site fires on the acting path."""
    for name in ("range/split-before-meta-commit",
                 "range/split-after-meta-commit",
                 "range/split-mid-wal-partition",
                 "range/split-before-parent-retire",
                 "range/auto-split"):
        assert name in failpoint.DECLARED, name
    rec = _hot_recorder()
    srv = _server(tmp_path, heat=rec, auto_split=True,
                  split_cooldown_ms=0)
    rec.set_specs(srv.specs)
    try:
        tso = TimestampOracle()
        router = RangeRouter(root=str(tmp_path))
        committer = TwoPhaseCommitter(router, tso)
        # arm the actuator site to ABORT the split: proves the hook
        # sits before any state change
        failpoint.enable("range/auto-split", RPCError("vetoed"))
        deadline = time.monotonic() + 20.0
        i = 0
        while time.monotonic() < deadline \
                and failpoint.hits("range/auto-split") == 0:
            _commit(committer, {b"fp%04d" % (i % 64): b"v"}, tso)
            i += 1
            srv._auto_split_tick()
        assert failpoint.hits("range/auto-split") >= 1
        assert len(srv.directory.load_specs()) == 2, \
            "vetoed auto-split still executed"
    finally:
        srv.close()


# ==================== inspection: range-split-flap ====================

def test_range_split_flap_rule(tmp_path):
    from tidb_tpu.obs_inspect import RULES, lint_rules
    from tidb_tpu.session import Session
    from tidb_tpu.store.storage import Storage

    assert lint_rules() == []
    assert "range-split-flap" in RULES
    st = Storage()
    s = Session(st)
    thr = st.diagnostics.split_flap_threshold
    # one honest split: silent
    st.obs.events.record("range_split",
                         "r1 -> r1+r3 at 6b30 epoch=2 trigger=auto")
    rows = [r for r in s.execute(
        "select rule, item, value from "
        "information_schema.inspection_result").rows
        if r[0] == "range-split-flap"]
    assert rows == []
    # a flapping range: threshold splits inside the window
    for t in range(thr):
        st.obs.events.record(
            "range_split",
            f"r1 -> r1+r{4 + t} at 6b3{t} epoch={3 + t} trigger=auto")
    rows = [r for r in s.execute(
        "select rule, item, value from "
        "information_schema.inspection_result").rows
        if r[0] == "range-split-flap"]
    assert rows and rows[0][1] == "r1"
    assert int(rows[0][2]) >= thr
    # threshold 0 disables the rule
    st.diagnostics.split_flap_threshold = 0
    st.diagnostics._status_cache = None
    rows = [r for r in s.execute(
        "select rule from information_schema.inspection_result").rows
        if r[0] == "range-split-flap"]
    assert rows == []
    st.close()


# ==================== knobs ====================

def _load_cfg(tmp_path, text):
    from tidb_tpu.config import Config
    p = tmp_path / "cfg.toml"
    p.write_text(text)
    return Config.load(str(p))


def test_split_knobs_parse_validate_seed_and_status(tmp_path):
    from tidb_tpu.config import Config, ConfigError

    cfg = _load_cfg(tmp_path, f"""
path = "{tmp_path / 'store'}"

[ranges]
enabled = true
count = 2
auto-split = true
split-cooldown-ms = 250
max-auto-splits = 9

[diagnostics]
split-flap-threshold = 5
split-flap-window-s = 60
""")
    cfg.validate()
    assert cfg.ranges.auto_split is True
    assert cfg.ranges.split_cooldown_ms == 250
    assert cfg.ranges.max_auto_splits == 9
    assert cfg.diagnostics.split_flap_threshold == 5
    assert cfg.diagnostics.split_flap_window_s == 60
    for bad in ("[ranges]\nsplit-cooldown-ms = -1\n",
                "[ranges]\nmax-auto-splits = -2\n",
                "[diagnostics]\nsplit-flap-threshold = -1\n",
                "[diagnostics]\nsplit-flap-window-s = -5\n"):
        with pytest.raises(ConfigError):
            _load_cfg(tmp_path, bad).validate()
    # the reloadable subset includes the actuator knobs
    assert {"ranges.auto_split", "ranges.split_cooldown_ms",
            "ranges.max_auto_splits"} <= Config.RELOADABLE

    # seed -> server fields -> /status; re-seed applies live
    from tidb_tpu.store.storage import Storage
    st = Storage(path=str(tmp_path / "store"))
    try:
        cfg.seed_ranges(st)
        assert st.ranges is not None
        assert st.ranges.server.auto_split is True
        assert st.ranges.server.split_cooldown_ms == 250
        assert st.ranges.server.max_auto_splits == 9
        status = st.ranges.status()
        assert status["auto_split"] is True
        assert status["split_cooldown_ms"] == 250
        assert status["max_auto_splits"] == 9
        cfg.ranges.auto_split = False
        cfg.ranges.split_cooldown_ms = 990
        cfg.seed_ranges(st)
        assert st.ranges.server.auto_split is False
        assert st.ranges.server.split_cooldown_ms == 990
    finally:
        st.close()


def test_diagnostics_split_flap_knobs_seed(tmp_path):
    from tidb_tpu.store.storage import Storage

    cfg = _load_cfg(
        tmp_path,
        "[diagnostics]\nsplit-flap-threshold = 7\n"
        "split-flap-window-s = 11\n")
    st = Storage()
    try:
        cfg.seed_diagnostics(st)
        assert st.diagnostics.split_flap_threshold == 7
        assert st.diagnostics.split_flap_window_s == 11
    finally:
        st.close()


def test_split_metric_family_registered_and_lint_clean():
    text = obs.PROCESS_METRICS.render()
    assert "tidb_range_splits_total" in text
    assert obs.lint_metrics([obs.PROCESS_METRICS]) == []
    # and the family is queryable through the metrics_schema tier
    from tidb_tpu.session import Session
    from tidb_tpu.store.storage import Storage
    obs.RANGE_SPLITS.inc(trigger="manual")
    st = Storage()
    try:
        rows = Session(st).execute(
            "select labels, value from "
            "metrics_schema.tidb_range_splits_total").rows
        assert any(r[0] == 'trigger="manual"' and r[1] >= 1
                   for r in rows)
    finally:
        st.close()
