"""Keyspace heat plane (ISSUE 18): per-range traffic histograms,
hot-range detection and load-based split advisories.

Pins the acceptance criteria: zero statement-path heat work while
[heatmap] enabled = false (poison test); the ring/bucket rotation
respects the caps; each feeding site (fastpath point read, coprocessor
scan, local 2PC commit, range-leader apply) lands in the RIGHT range
cell; a deliberately skewed write workload against a 4-range store
produces a `hot-range` finding in information_schema.inspection_result
plus a `range-split-advisory` whose split key falls inside the hot
range's observed key span; uniform load stays silent; the cluster_
table fans out with per-peer degradation; and the [heatmap] knobs
parse/seed/hot-reload. The conftest guard covers leaked threads (the
recorder has none of its own — rotation is lazy)."""

from __future__ import annotations

import dataclasses
import json

import pytest

from tidb_tpu import obs, obs_heat, obs_inspect
from tidb_tpu.config import Config, ConfigError, HeatmapConfig
from tidb_tpu.kv import tablecodec
from tidb_tpu.kv.rangemeta import split_keyspace
from tidb_tpu.obs_heat import RangeHeatRecorder
from tidb_tpu.rpc.client import RpcOptions
from tidb_tpu.session import Session
from tidb_tpu.store.storage import Storage
from tidb_tpu.util import failpoint

OPTS = RpcOptions(connect_timeout_ms=1000, request_timeout_ms=4000,
                  backoff_budget_ms=3000, lock_budget_ms=8000,
                  lease_ms=2000)


@pytest.fixture(autouse=True)
def _clean_failpoints():
    yield
    failpoint.disable_all()


class _Clock:
    """Controls obs_heat's wall clock so bucket rotation is
    deterministic (the module only calls time.time())."""

    def __init__(self, t=1_000_000.0):
        self.t = float(t)

    def time(self):
        return self.t


class _Events:
    def __init__(self):
        self.rows = []

    def record(self, kind, detail="", severity="info", **kw):
        self.rows.append((kind, detail, severity))


def _recorder(monkeypatch, **knobs):
    clock = _Clock()
    monkeypatch.setattr(obs_heat, "time", clock)
    ev = _Events()
    h = RangeHeatRecorder(events=ev)
    h.configure(enabled=True, **knobs)
    return h, clock, ev


# ==================== config / state mirror ====================

def test_state_mirrors_config_section():
    """config.HeatmapConfig and obs_heat.RangeHeatRecorder are mirrored
    definitions (config never imports the obs chain): every knob must
    exist on the recorder with the same default, so seed_heatmap cannot
    silently drop one."""
    h = RangeHeatRecorder()
    for f in dataclasses.fields(HeatmapConfig):
        assert hasattr(h, f.name), f"RangeHeatRecorder lacks {f.name}"
        assert getattr(h, f.name) == f.default, f.name


def test_heatmap_knobs_parse_seed_and_reload():
    cfg = Config()
    cfg.apply({"heatmap": {"enabled": True, "bucket-seconds": 2,
                           "ring-buckets": 5, "hot-ratio": 3.0,
                           "sustained-buckets": 1,
                           "key-sample-cap": 8}})
    cfg.validate()
    st = Storage()
    try:
        cfg.seed_heatmap(st)
        assert st.heat.enabled is True
        assert st.heat.bucket_seconds == 2
        assert st.heat.ring_buckets == 5
        assert st.heat.hot_ratio == 3.0
        assert st.heat.sustained_buckets == 1
        assert st.heat.key_sample_cap == 8
        # SIGHUP: disabling reloads live too
        cfg.heatmap.enabled = False
        cfg.seed_heatmap(st)
        assert st.heat.enabled is False
    finally:
        st.close()
    for knob in ("heatmap.enabled", "heatmap.bucket_seconds",
                 "heatmap.ring_buckets", "heatmap.hot_ratio",
                 "heatmap.sustained_buckets", "heatmap.key_sample_cap"):
        assert knob in Config.RELOADABLE, knob
    # validation rejects nonsense
    for field_, bad, msg in (("hot_ratio", 0.5, "hot-ratio"),
                             ("ring_buckets", 1, "ring-buckets"),
                             ("key_sample_cap", 1, "key-sample-cap")):
        c = Config()
        setattr(c.heatmap, field_, bad)
        with pytest.raises(ConfigError, match=msg):
            c.validate()


# ==================== zero work while disabled ====================

def test_disabled_does_zero_heat_work(monkeypatch):
    st = Storage()
    try:
        assert st.heat.enabled is False  # the Top SQL default

        def boom(*a, **k):
            raise AssertionError("heat touched while disabled")

        # poison every accounting entry point AND the cell machinery;
        # the note_* prologues and the call-site `.enabled` gates must
        # keep statements from ever reaching them
        for name in ("_cell", "_rotate", "_sample", "_detect"):
            monkeypatch.setattr(st.heat, name, boom)
        s = Session(st)
        s.execute("create table z (id bigint primary key, v bigint)")
        s.execute("insert into z values (1, 10), (2, 20)")
        s.execute("select v from z where id = 2")  # fastpath point read
        s.execute("select sum(v) from z")          # coprocessor scan
        assert st.heat.findings() == []
        assert st.heat.table_rows() == []
        assert st.diag.diag_hot_ranges() == {"rows": []}
        payload = st.heat.debug_payload()
        assert payload["enabled"] is False and "buckets" not in payload
        rows = s.execute("select * from "
                         "information_schema.tidb_hot_ranges").rows
        assert rows == []
    finally:
        st.close()


# ==================== rotation + caps ====================

def test_ring_rotation_respects_caps(monkeypatch):
    h, clock, _ = _recorder(monkeypatch, bucket_seconds=1,
                            ring_buckets=3)
    for i in range(10):
        clock.t = 1_000_000.0 + i
        h.note_read(b"k", rows=1, nbytes=1)
    assert len(h._ring) == 3  # oldest buckets dropped
    assert [b["start"] for b in h._ring] == [1_000_007, 1_000_008,
                                             1_000_009]
    # lifetime totals survive rotation
    assert h.range_totals(1) == (10, 10, 0, 0)
    # shrinking the ring live drops the oldest immediately
    h.configure(ring_buckets=2)
    assert len(h._ring) == 2
    # knob clamps: nonsense inputs degrade to the documented floors
    h.configure(bucket_seconds=0, ring_buckets=1, hot_ratio=0.2,
                sustained_buckets=0, key_sample_cap=1)
    assert h.bucket_seconds == 1 and h.ring_buckets == 2
    assert h.hot_ratio == 1.0 and h.sustained_buckets == 1
    assert h.key_sample_cap == 2


def test_key_sample_bounded_and_weighted(monkeypatch):
    h, _, _ = _recorder(monkeypatch, key_sample_cap=4)
    h.note_write([(b"k%03d" % i, 1) for i in range(32)])
    s = h._samples[1]
    assert len(s["order"]) == 4 and len(s["keys"]) == 4
    assert s["n"] == 32
    # re-observing a sampled key adds weight instead of a slot
    kept = s["order"][0]
    before = s["keys"][kept]
    h.note_write([(kept, 9)])
    assert s["keys"][kept] == before + 10  # weight = 1 + value bytes
    assert len(s["order"]) == 4


# ==================== per-site attribution ====================

def test_sites_land_in_the_right_cell():
    st = Storage()
    try:
        s = Session(st)
        s.execute("create table t (id bigint primary key, v bigint)")
        tid = st.catalog.table("test", "t").id
        # two ranges split inside t's handle space at handle 50
        st.heat.set_specs(split_keyspace(
            1, [tablecodec.record_key(tid, 50)]))
        st.heat.configure(enabled=True, bucket_seconds=3600)
        # 2PC commits (the LOCAL committer carries the recorder):
        # 3 rows left of the split, 3 right of it
        s.execute("insert into t values (1, 10), (2, 20), (3, 30)")
        s.execute("insert into t values "
                  "(100, 1), (101, 2), (102, 3)")
        w1 = st.heat.range_totals(1)
        w2 = st.heat.range_totals(2)
        assert w1[2] == 3 and w1[3] > 0, w1  # write rows/bytes, r1
        assert w2[2] == 3 and w2[3] > 0, w2
        assert w1[0] == w2[0] == 0  # no reads yet
        # fastpath point read routes by the ROW's record key
        s.execute("select v from t where id = 101")
        assert list(s.last_engines) == ["point"], s.last_engines
        assert st.heat.range_totals(1)[0] == 0
        assert st.heat.range_totals(2)[0] == 1
        # a coprocessor scan splits across every overlapped range
        s.execute("select sum(v) from t")
        r1 = st.heat.range_totals(1)[0]
        r2 = st.heat.range_totals(2)[0]
        assert r1 >= 1 and r2 >= 2, (r1, r2)
        # the metric families carry the same per-range attribution
        fams = st.obs.metrics.families()
        for fam in ("tidb_range_read_rows_total",
                    "tidb_range_read_bytes_total",
                    "tidb_range_write_rows_total",
                    "tidb_range_write_bytes_total",
                    "tidb_hot_range_ratio"):
            assert fam in fams, fam
        assert 'range="2"' in st.obs.metrics.render()
        assert obs.lint_metrics([st.obs.metrics]) == []
    finally:
        st.close()


def test_note_range_is_the_leader_feed(monkeypatch):
    """rpc/ranged.py's leader apply uses the direct cell feed: no key
    routing, keys feed the split sample at weight 1."""
    h, _, _ = _recorder(monkeypatch)
    h.set_specs(split_keyspace(4))
    h.note_range(3, write_rows=5, write_bytes=50,
                 keys=[b"\x81a", b"\x81b"])
    h.note_range(3, read_rows=2, read_bytes=16)
    assert h.range_totals(3) == (2, 16, 5, 50)
    assert h.range_totals(1) == (0, 0, 0, 0)
    assert sorted(h._samples[3]["keys"]) == [b"\x81a", b"\x81b"]


# ==================== hot detection + split advisory ====================

def test_uniform_load_stays_silent(monkeypatch):
    h, clock, ev = _recorder(monkeypatch, bucket_seconds=1,
                             sustained_buckets=1)
    h.set_specs(split_keyspace(4))
    for i in range(4):
        prefix = bytes([0x40 * i + 1])
        h.note_write([(prefix + b"%02d" % j, 8) for j in range(20)])
    assert h.findings() == []
    clock.t += 1
    h.note_read(b"\x01", 1, 1)  # rotate: detection on the closed bucket
    assert not [r for r in ev.rows if r[0] == "hot_range"], ev.rows


def test_skew_fires_hot_range_and_advisory(monkeypatch):
    h, clock, ev = _recorder(monkeypatch, bucket_seconds=1,
                             sustained_buckets=1, hot_ratio=8.0)
    h.set_specs(split_keyspace(4))
    spec = next(s for s in h._specs if s.id == 2)

    def skew(n=40):
        # all writes into range 2's span, two distinct key groups
        h.note_write([(spec.start_key + b"%02d" % (j % 10), 8)
                      for j in range(n)])

    skew()
    # on-demand view: hot NOW, without waiting out a bucket
    found = {f["rule"]: f for f in h.findings()}
    assert found["hot-range"]["item"] == "r2"
    adv = found["range-split-advisory"]
    assert adv["item"] == "r2" and adv["severity"] == "info"
    split = bytes.fromhex(adv["value"])
    sampled = sorted(h._samples[2]["keys"])
    # the advisory partitions the OBSERVED span: strictly above the
    # smallest sampled key, at most the largest
    assert sampled[0] < split <= sampled[-1], (sampled, split)
    # rotation closes the bucket -> ONE edge-triggered event
    clock.t += 1
    h.note_write([(spec.start_key, 1)])
    assert [r[0] for r in ev.rows].count("hot_range") == 1
    # still hot next bucket: no re-fire while the edge is held
    skew()
    clock.t += 1
    skew()
    assert [r[0] for r in ev.rows].count("hot_range") == 1
    # a cold bucket re-arms the trigger, the next hot one fires again
    clock.t += 1
    h.note_read(b"\x01", 1, 1)   # rotate over an (almost) silent bucket
    clock.t += 1
    skew()
    clock.t += 1
    h.note_read(b"\x01", 1, 1)
    assert [r[0] for r in ev.rows].count("hot_range") == 2, ev.rows
    skew()  # make the LIVE bucket hot again for the on-demand views
    # table rows carry the hot flag + advisory; payload is JSON-safe
    rows = {r[0]: r for r in h.table_rows()}
    assert rows[2][8] == 1 and rows[2][9] is not None
    assert rows[1][8] == 0 and rows[1][9] is None
    payload = h.debug_payload()
    json.dumps(payload)
    assert len(payload["heatmap"]) == 4  # one shade line per range
    assert any("@" in line for line in payload["heatmap"])


def test_one_key_hotspot_has_no_advisory(monkeypatch):
    """A single hammered key cannot be split — advisory stays None
    (that is the salted-key case, a later PR's actuator)."""
    h, _, _ = _recorder(monkeypatch, sustained_buckets=1)
    h.set_specs(split_keyspace(4))
    h.note_write([(b"\x01same", 8)] * 50)
    assert h.split_advisory(1) is None
    rules = [f["rule"] for f in h.findings()]
    assert rules == ["hot-range"], rules


# ==================== acceptance: 4-range store end to end ==========

def test_skewed_writes_on_4_range_store_reach_inspection(tmp_path):
    st = Storage(str(tmp_path))
    try:
        s = Session(st)
        s.execute("create table t (id bigint primary key, v bigint)")
        tid = st.catalog.table("test", "t").id
        splits = [tablecodec.record_key(tid, h) for h in (25, 50, 75)]
        st.arm_ranges(enabled=True, split_points=splits)
        assert st.ranges is not None
        assert len(st.heat._specs) == 4  # arm_ranges adopted the table
        st.heat.configure(enabled=True, bucket_seconds=3600,
                          sustained_buckets=1, hot_ratio=8.0)
        # skew: every write lands in range 3 = [key(50), key(75))
        for h in range(50, 74, 4):
            s.execute("insert into t values " + ", ".join(
                f"({h + i}, {i})" for i in range(4)))
        rows = s.execute(
            "select rule, item, value from "
            "information_schema.inspection_result").rows
        hot = [r for r in rows if r[0] == "hot-range"]
        adv = [r for r in rows if r[0] == "range-split-advisory"]
        assert hot and hot[0][1] == "r3", rows
        assert adv and adv[0][1] == "r3", rows
        # the recommended split key falls inside the hot range's
        # OBSERVED key span: a record key of t, handle in (50, 74)
        key = bytes.fromhex(adv[0][2])
        assert splits[1] <= key < splits[2]
        ktid, handle = tablecodec.decode_record_key(key)
        assert ktid == tid and 50 < handle < 74, (ktid, handle)
        # the same heat reaches the SQL matrix + the range describe()
        hr = {r[0]: r for r in s.execute(
            "select * from "
            "information_schema.tidb_hot_ranges").rows}
        assert hr[3][8] == 1 and hr[3][5] == 24, hr  # hot, write_rows
        assert hr[1][8] == 0
        ci = [r for r in s.execute(
            "select type, range_id, range_write_rows from "
            "information_schema.cluster_info").rows
            if r[0] == "range"]
        assert {r[1]: r[2] for r in ci}[3] == 24, ci
    finally:
        st.close()


# ==================== cluster fan-out ====================

@pytest.fixture()
def cluster(tmp_path):
    leader = Storage(str(tmp_path / "leader"), shared=True,
                     rpc_listen="127.0.0.1:0", rpc_options=OPTS)
    follower = Storage(str(tmp_path / "follower"),
                       remote=f"127.0.0.1:{leader.rpc_server.port}",
                       rpc_options=OPTS)
    try:
        yield leader, follower
    finally:
        follower.close()
        leader.close()


def test_cluster_hot_ranges_from_both_members(cluster):
    leader, follower = cluster
    for st, reads in ((leader, 3), (follower, 7)):
        st.heat.configure(enabled=True, bucket_seconds=3600)
        st.heat.note_read(b"k", rows=reads, nbytes=reads * 8)
    sl = Session(leader)
    rows = sl.execute(
        "select instance, range_id, read_rows, error from "
        "information_schema.cluster_hot_ranges").rows
    by_inst = {r[0]: r[2] for r in rows if r[3] is None}
    assert by_inst == {leader.diag_address: 3,
                       follower.diag_address: 7}, rows
    assert all(r[1] == 1 for r in rows if r[3] is None)


def test_cluster_hot_ranges_peer_down_degrades(cluster):
    leader, follower = cluster
    leader.heat.configure(enabled=True)
    follower.heat.configure(enabled=True)
    sl = Session(leader)
    failpoint.enable("diag/peer-down")
    try:
        rows = sl.execute(
            "select instance, error from "
            "information_schema.cluster_hot_ranges").rows
    finally:
        failpoint.disable("diag/peer-down")
    err = [r for r in rows if r[1] is not None]
    assert err and any(follower.diag_address == r[0] for r in err), rows
    assert any("unreachable" in w[2] for w in sl.warnings), sl.warnings


# ==================== lint coverage (CI/tooling satellite) =========

def test_heat_rules_and_metrics_pass_registry_lints():
    """The heat surfaces ride the existing lint planes: both inspection
    rules are registered kebab-cased with heatmap-knob references, the
    tidb_range_*/tidb_hot_range_ratio families pass the metric-hygiene
    lint on a live registry, and the [heatmap] knobs are inside the
    config-knob-drift rule's coverage."""
    assert "hot-range" in obs_inspect.RULES
    assert "range-split-advisory" in obs_inspect.RULES
    assert obs_inspect.lint_rules() == []
    for rule in ("hot-range", "range-split-advisory"):
        assert "heatmap" in obs_inspect.RULES[rule].reference
    from tidb_tpu.config import EXAMPLE
    assert "[heatmap]" in EXAMPLE and "hot-ratio" in EXAMPLE
    assert "key-sample-cap" in EXAMPLE
