"""Scalar function library: string/math/date/control + session functions.

Counterpart of the reference's builtin families (reference:
expression/builtin_string.go, builtin_math.go, builtin_time.go,
builtin_compare.go, builtin_info.go)."""

from __future__ import annotations

import pytest

from testkit import TestKit


@pytest.fixture()
def tk():
    k = TestKit()
    k.must_exec("create table t (id int primary key, s varchar(20), "
                "d decimal(8,2), f double, dt date, ts datetime)")
    k.must_exec("insert into t values (1, 'Hello World', 123.45, 2.5, "
                "'2024-02-15', '2024-02-15 13:45:30'), "
                "(2, NULL, -7.89, 0.0, '2023-12-31', "
                "'2023-12-31 23:59:59')")
    return k


def _one(tk, sql):
    return tk.must_query(sql + " from t where id = 1")[0]


def test_string_functions(tk):
    assert _one(tk, "select upper(s), lower(s), reverse(s)") == \
        ("HELLO WORLD", "hello world", "dlroW olleH")
    assert _one(tk, "select length(s), char_length(s), ascii(s)") == \
        (11, 11, 72)
    assert _one(tk, "select concat(s, '!', id), "
                    "concat_ws('-', 'a', s, 'z')") == \
        ("Hello World!1", "a-Hello World-z")
    assert _one(tk, "select left(s, 5), right(s, 5), repeat('ab', 3)") == \
        ("Hello", "World", "ababab")
    assert _one(tk, "select replace(s, 'World', 'There'), "
                    "trim('  x  '), ltrim('  x'), rtrim('x  ')") == \
        ("Hello There", "x", "x", "x")
    assert _one(tk, "select lpad('5', 3, '0'), rpad('ab', 5, 'xy')") == \
        ("005", "abxyx")
    assert _one(tk, "select locate('World', s), instr(s, 'World'), "
                    "locate('zz', s)") == (7, 7, 0)


def test_string_null_propagation(tk):
    # CONCAT: NULL poison; CONCAT_WS: NULL args skipped
    assert tk.must_query(
        "select concat(s, 'x'), concat_ws(',', 'a', s, 'b') "
        "from t where id = 2") == [(None, "a,b")]
    assert tk.must_query(
        "select upper(s) from t where id = 2") == [(None,)]


def test_math_functions(tk):
    r = _one(tk, "select round(d), round(d, 1), truncate(d, 1), "
                 "floor(d), ceil(d)")
    assert (str(r[0]), str(r[1]), str(r[2]), r[3], r[4]) == \
        ("123", "123.5", "123.4", 123, 124)
    # negative decimals round away from zero, floor/ceil flip
    r = tk.must_query("select round(d, 1), floor(d), ceil(d) from t "
                      "where id = 2")[0]
    assert (str(r[0]), r[1], r[2]) == ("-7.9", -8, -7)
    assert str(_one(tk, "select round(2.5)")[0]) == "3"  # half away
    r = _one(tk, "select sqrt(16), pow(2, 10), exp(0), sign(-3), "
                 "sign(0), sign(9)")
    assert r == (4.0, 1024.0, 1.0, -1, 0, 1)
    r = _one(tk, "select log2(8), log10(1000), log(3, 81), ln(1)")
    assert r == (3.0, 3.0, 4.0, 0.0)
    # out-of-domain -> NULL
    assert _one(tk, "select sqrt(0 - 1), ln(0)") == (None, None)
    assert _one(tk, "select round(f, 2), floor(f), ceil(f)") == \
        (2.5, 2.0, 3.0)
    assert abs(_one(tk, "select pi()")[0] - 3.14159265) < 1e-6


def test_greatest_least_nullif(tk):
    assert _one(tk, "select greatest(1, 5, 3), least(1, 5, 3)") == (5, 1)
    assert _one(tk, "select greatest(1.5, d, 2)") == \
        _one(tk, "select d")
    assert _one(tk, "select greatest(1, s is null, 3), least(id, 0)") == \
        (3, 0)
    # MySQL: any NULL operand -> NULL
    assert tk.must_query("select greatest(1, s is not null, 3) "
                         "from t where id = 1") == [(3,)]
    assert _one(tk, "select nullif(id, 1), nullif(id, 9)") == (None, 1)


def test_date_functions(tk):
    # 2024-02-15 is a Thursday in Q1, day 46 of a leap year
    assert _one(tk, "select dayofweek(dt), weekday(dt), dayofyear(dt), "
                    "quarter(dt)") == (5, 3, 46, 1)
    assert _one(tk, "select hour(ts), minute(ts), second(ts)") == \
        (13, 45, 30)
    r = _one(tk, "select date(ts), last_day(dt), "
                 "datediff(dt, '2024-01-01')")
    assert (str(r[0]), str(r[1]), r[2]) == \
        ("2024-02-15", "2024-02-29", 45)
    # functions compose with WHERE
    assert tk.must_query(
        "select id from t where quarter(dt) = 4") == [(2,)]


def test_session_functions(tk):
    r = tk.must_query("select version(), database(), user()")[0]
    assert "TiDB" in r[0] and r[1] == "test" and "@" in r[2]
    now = tk.must_query("select now(), curdate(), current_date")[0]
    assert now[0][:4] == now[1][:4]
    # NOW() keeps the statement out of the plan cache
    h = tk.session.plan_cache_hits
    tk.must_query("select now()")
    tk.must_query("select now()")
    assert tk.session.plan_cache_hits == h


def test_review_edge_cases(tk):
    # string GREATEST/LEAST compares strings, not dictionary codes
    assert _one(tk, "select greatest(s, 'Zz'), least(s, 'Aa')") == \
        ("Zz", "Aa")
    # ROUND with NULL digits -> NULL
    assert _one(tk, "select round(d, null)") == (None,)
    # string literals coerce for date functions
    assert tk.must_query(
        "select dayofweek('2024-02-15'), last_day('2024-02-15'), "
        "hour('26:30:00')")[0][0:1] == (5,)
    r = tk.must_query("select hour('26:30:00'), hour('-01:30:00')")[0]
    assert r == (26, 1)
    # LPAD negative length -> NULL
    assert tk.must_query("select lpad('hi', 0-1, 'x')") == [(None,)]


def test_ci_collation_string_functions():
    tk2 = TestKit()
    tk2.must_exec("create table ci (s varchar(30) collate "
                  "utf8mb4_general_ci)")
    tk2.must_exec("insert into ci values ('Hello World')")
    assert tk2.must_query(
        "select locate('hello', s), instr(s, 'WORLD') from ci") == \
        [(1, 7)]
    assert tk2.must_query(
        "select replace(s, 'WORLD', 'x') from ci") == [("Hello x",)]


def test_functions_in_group_by_and_order(tk):
    tk.must_exec("create table g (w varchar(10), v int)")
    tk.must_exec("insert into g values ('aa',1),('AA',2),('bb',3)")
    rows = tk.must_query(
        "select upper(w), sum(v) from g group by upper(w) "
        "order by upper(w)")
    assert rows == [("AA", 3), ("BB", 3)]
    rows = tk.must_query("select w from g order by lower(w), v")
    assert rows == [("aa",), ("AA",), ("bb",)]
