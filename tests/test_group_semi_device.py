"""Device all-groups aggregation + semi/anti membership joins (ISSUE 14).

The sorted-run candidate machinery now serves ANY grouped aggregation
whose dense segment space fails (mode "group": sort + segment-reduce
with a cap-checked candidate buffer, min/max riding the sort as an
extra operand), and EXISTS/IN/NOT IN edges over bare scans fuse into
fragments as device membership bitmaps (NULL-aware for NOT IN). Both
must be BIT-IDENTICAL to independent host oracles — the pre-existing
host aggregation/join paths with the new device recognition forced
off — on single-device, tiled, and 8-way-mesh execution.
"""

import unittest.mock as mock

import jax
import numpy as np
import pytest

from tidb_tpu.copr import fragment as FR
from tidb_tpu.copr import mesh as M
from tidb_tpu.copr.client import CopClient
from tidb_tpu.plan import fragment as PF
from tidb_tpu.plan.fragment import FragmentDAG
from tidb_tpu.session import Session

N_FACT = 9_000
N_DIM = 2_000

GROUP_QUERIES = [
    # single wide key (card ~50k >> 8192 dense cap) via the lifted
    # single-table fragment escape
    "select a, sum(v), count(w) from f group by a order by a",
    # two wide keys + NULLs in b (NULL group) and in w (count gaps)
    "select a, b, sum(v) from f group by a, b order by a, b",
    # min/max ride the sort as an extra operand; w has NULLs so some
    # groups aggregate over gaps (and single-NULL groups are NULL)
    "select a, min(w) from f group by a order by a",
    "select a, max(w), sum(v) from f group by a order by a",
    "select a, min(v) from f group by a order by a",
    # join + wide group space: the fragment path's group mode
    "select a, x, sum(v) from f, d where f.g = d.g "
    "group by a, x order by a, x",
    # HAVING over an UNORDERED epoch: previously host-only (the
    # having path existed only in rank space), now the sort body
    "select a, sum(v) s from f group by a having s > 500 order by a",
]

# clustered group key (storage order == key order): the rank-space
# streamseg body serves the all-groups mode
RUNORD_QUERY = "select k2, sum(v2) from f2 group by k2 order by k2 limit 40"

SEMI_QUERIES = [
    # IN over a filtered subquery key (nullable build key: NULLs in the
    # set never match a SEMI probe)
    "select k, a from f where a in (select kk from d2 where x > 5) "
    "order by k limit 80",
    # correlated EXISTS (decorrelates to the same SEMI shape)
    "select k from f where exists (select * from d2 "
    "where d2.kk = f.a and d2.x > 5) order by k limit 80",
    # NULL probe keys (b) are filtered by IN
    "select k from f where b in (select kk from d2 where x > 5) "
    "order by k limit 80",
    # NOT EXISTS -> plain ANTI (NULL probe keys kept)
    "select k from f where not exists (select * from d2 "
    "where d2.kk = f.a) order by k limit 80",
    # NULL-aware NOT IN: the build set contains NULL -> empty result
    "select k from f where a not in (select kk from d2 where x > 5) "
    "order by k limit 80",
    # NOT IN over a NULL-free filtered set
    "select k from f where a not in (select kk from d2 "
    "where x > 5 and kk is not null) order by k limit 80",
    # NOT IN (empty set) is TRUE for every row, NULL probe keys included
    "select k from f where b not in (select kk from d2 where x > 9000) "
    "order by k limit 80",
    # fused agg over a semi gate (dense groups -> mode agg+semi)
    "select c, count(*) from f where exists (select * from d2 "
    "where d2.kk = f.a and d2.x > 5) group by c order by c",
    # wide groups over a semi gate -> group+semi
    "select a, count(*) from f where exists (select * from d2 "
    "where d2.kk = f.a and d2.x > 5) group by a order by a",
]


def _bulk(session, name, ddl, cols, valids=None):
    session.execute(ddl)
    info = session.catalog.table("test", name)
    store = session.storage.table_store(info.id)
    store.bulk_load(cols, valids)
    return store


@pytest.fixture(scope="module")
def corpus():
    rng = np.random.default_rng(41)
    base = Session(cop=CopClient())
    k = np.arange(N_FACT, dtype=np.int64)
    a = rng.integers(0, 50_000, N_FACT)
    b = rng.integers(0, 30_000, N_FACT)
    b_valid = rng.random(N_FACT) > 0.15
    v = rng.integers(-40_000, 40_000, N_FACT)  # decimal(9,2) unscaled
    w = rng.integers(-500, 500, N_FACT)
    w_valid = rng.random(N_FACT) > 0.2
    c = rng.integers(0, 5, N_FACT)
    g = rng.integers(0, N_DIM, N_FACT)
    _bulk(base, "f",
          "create table f (k bigint primary key, a int, b int, "
          "v decimal(9,2), w int, c int, g int)",
          [k, a, b, v, w, c, g],
          [None, None, b_valid, None, w_valid, None, None])
    dg = np.arange(N_DIM, dtype=np.int64)
    x = rng.integers(0, 60_000, N_DIM)
    _bulk(base, "d",
          "create table d (g bigint primary key, x int)", [dg, x])
    did = np.arange(N_DIM, dtype=np.int64)
    kk = rng.integers(0, 50_000, N_DIM)
    kk_valid = rng.random(N_DIM) > 0.1
    dx = rng.integers(0, 100, N_DIM)
    _bulk(base, "d2",
          "create table d2 (id bigint primary key, kk int, x int)",
          [did, kk, dx], [None, kk_valid, None])
    # clustered wide group key for the rank-space all-groups body
    k2 = np.repeat(np.arange(4_000, dtype=np.int64), 3)[:N_FACT]
    _bulk(base, "f2",
          "create table f2 (id bigint primary key, k2 int, v2 int)",
          [np.arange(len(k2), dtype=np.int64), k2,
           rng.integers(-100, 100, len(k2))])
    return base


def _host_oracle(corpus, queries):
    """Results with the NEW device recognition forced off: grouped
    queries run the pre-existing host aggregation, semi queries the
    engine's _run_join (recognition disabled at the PLAN level), so
    the oracle never touches the code under test."""
    host = Session(corpus.storage, cop=CopClient())

    def deny_frag(cop, frag, snaps):
        raise FR._Fallback("forced-host")

    def deny_lift(self, dag, snap, reason):
        return None

    out = {}
    with mock.patch.object(PF, "_semi_build_leaf",
                           lambda node: None), \
            mock.patch.object(FR, "_device_fragment", deny_frag), \
            mock.patch.object(CopClient, "_try_group_fragment",
                              deny_lift):
        for sql in queries:
            out[sql] = host.query(sql)
    return out


@pytest.fixture(scope="module")
def host_results(corpus):
    return _host_oracle(corpus,
                        GROUP_QUERIES + SEMI_QUERIES + [RUNORD_QUERY])


_MODE_SESSIONS: dict = {}


def _mode_session(corpus, mode):
    s = _MODE_SESSIONS.get(mode)
    if s is not None and s.storage is corpus.storage:
        return s
    if mode == "single":
        s = Session(corpus.storage, cop=CopClient())
    elif mode == "tiled":
        cop = CopClient()
        cop.TILE_ROWS = 2048
        s = Session(corpus.storage, cop=cop)
    else:
        assert len(jax.devices()) >= 8, "conftest must provide 8 devices"
        plane = M.MeshPlane(M.MeshConfig(enabled=True,
                                         shard_threshold_rows=512))
        s = Session(corpus.storage, cop=plane.client_for(corpus.storage))
    _MODE_SESSIONS[mode] = s
    return s


def _engines(session, sql):
    return {r[3] for r in session.execute(
        "EXPLAIN ANALYZE " + sql).rows if r[3]}


@pytest.mark.parametrize("mode", ["single", "tiled", "mesh"])
class TestBitIdenticalVsHost:
    def test_group_mode(self, corpus, host_results, mode):
        s = _mode_session(corpus, mode)
        for sql in GROUP_QUERIES:
            assert s.query(sql) == host_results[sql], (mode, sql)
        eng = _engines(s, GROUP_QUERIES[0])
        assert any("device[group" in e for e in eng), (mode, eng)

    def test_semi_joins(self, corpus, host_results, mode):
        s = _mode_session(corpus, mode)
        for sql in SEMI_QUERIES:
            assert s.query(sql) == host_results[sql], (mode, sql)
        assert any("+semi" in e for e in _engines(s, SEMI_QUERIES[0])), \
            mode

    def test_runordered_group_mode(self, corpus, host_results, mode):
        s = _mode_session(corpus, mode)
        assert s.query(RUNORD_QUERY) == host_results[RUNORD_QUERY], mode


def test_semi_engine_tags(corpus, host_results):
    """The fused modes actually engage: row fragments with membership
    gates tag rows+semi, dense aggregation over a gate tags agg+semi,
    wide groups over a gate tag group+semi."""
    s = _mode_session(corpus, "single")
    assert any("device[rows+semi]" in e
               for e in _engines(s, SEMI_QUERIES[0]))
    assert any("device[agg+semi]" in e
               for e in _engines(s, SEMI_QUERIES[7]))
    assert any("device[group+semi]" in e
               for e in _engines(s, SEMI_QUERIES[8]))


def test_group_overflow_falls_back(corpus, host_results):
    """More groups than the candidate buffer: the decode must detect
    the exhausted buffer and fall back to the host — never silently
    drop groups."""
    s = Session(corpus.storage, cop=CopClient())
    sql = GROUP_QUERIES[0]
    with mock.patch.object(FragmentDAG, "HAVING_CAP", 64):
        got = s.query(sql)
        eng = _engines(s, sql)
    assert got == host_results[sql]
    assert any(e.startswith("host(") for e in eng), eng


def test_group_mode_respects_mvcc_overlay(corpus, host_results):
    """Uncommitted probe rows (overlay batch) gate the candidate path
    out: results must still be exact through the host fallback."""
    s = Session(corpus.storage, cop=CopClient())
    sql = "select a, sum(v) from f group by a order by a"
    base = s.query(sql)
    s.execute("begin")
    try:
        s.execute("insert into f values "
                  "(9000001, 123456, 1, 7.50, 3, 1, 0)")
        got = s.query(sql)
        assert any(r[0] == 123456 for r in got)
        assert len(got) == len(base) + 1
    finally:
        s.execute("rollback")
    assert s.query(sql) == base


# ==================== dense-vs-sort strategy gate (ISSUE 15) =========

@pytest.fixture(scope="module")
def sparse_corpus():
    """A GROUP BY the dense einsum would happily serve (4096 dense
    int32 slots) but with ~2 rows/slot estimated occupancy — the
    mostly-empty one-hot shape the occupancy gate reroutes."""
    rng = np.random.default_rng(53)
    s = Session(cop=CopClient())
    n = 9_000
    _bulk(s, "sp",
          "create table sp (id bigint primary key, a3 int, b3 int, "
          "v3 int)",
          [np.arange(n, dtype=np.int64),
           rng.integers(0, 64, n), rng.integers(0, 64, n),
           rng.integers(-1000, 1000, n)])
    return s


def test_sparse_einsum_reroutes_to_group_mode(sparse_corpus):
    """Occupancy below the per-slot floor flips the strategy to the
    sorted-run group mode, bit-identically to the dense einsum it
    replaces; the engine tag records the chosen strategy (which the
    workload-history plane persists per digest)."""
    from tidb_tpu.copr import client as C

    s = sparse_corpus
    sql = "select a3, b3, sum(v3) from sp group by a3, b3 " \
          "order by a3, b3"
    with mock.patch.object(C, "DENSE_MIN_ROWS_PER_SEGMENT", 0):
        want = s.query(sql)  # the dense einsum, gate disarmed
        assert _engines(s, sql) == {"device"}
    got = s.query(sql)
    eng = _engines(s, sql)
    assert got == want
    assert any("device[group" in e for e in eng), eng


def test_sparse_gate_retries_dense_when_group_ineligible(sparse_corpus):
    """A sparse space whose aggregate cannot flow through the group
    fragment (hll sketches) must RETRY the dense einsum — the gate may
    never create a host fallback."""
    s = sparse_corpus
    sql = "select a3, b3, approx_count_distinct(v3) from sp " \
          "group by a3, b3 order by a3, b3"
    got = s.query(sql)
    eng = _engines(s, sql)
    assert not any(e.startswith("host(") for e in eng), eng
    assert any(e.startswith("device") for e in eng), eng
    assert len(got) > 3000  # the space really is ~2 rows/slot wide
