"""Sequences, foreign-key metadata, and owner election.

Counterpart of the reference's ddl/sequence.go, ddl/foreign_key.go
(v5.0: FK metadata stored, NOT enforced) and owner/manager.go (mock at
owner/mock.go:35; flock replaces etcd leases for shared-dir
multi-process)."""

from __future__ import annotations

import pytest

from tidb_tpu.owner import FileLockOwnerManager, MockOwnerManager
from tidb_tpu.session import Session
from tidb_tpu.store.storage import Storage

from testkit import TestKit


def test_sequence_basics():
    tk = TestKit()
    tk.must_exec("create sequence sq start with 10 increment by 2")
    assert tk.must_query("select nextval(sq), nextval(sq)") == [(10, 12)]
    assert tk.must_query("select lastval(sq)") == [(12,)]
    tk.must_exec("select setval(sq, 100)")
    assert tk.must_query("select nextval(sq)") == [(102,)]
    assert tk.must_query(
        "select sequence_name, start_value, increment from "
        "information_schema.sequences") == [("sq", 10, 2)]
    # duplicate / drop
    with pytest.raises(Exception, match="exists"):
        tk.must_exec("create sequence sq")
    tk.must_exec("create sequence if not exists sq")
    tk.must_exec("drop sequence sq")
    with pytest.raises(Exception, match="unknown sequence"):
        tk.must_query("select nextval(sq)")


def test_sequence_in_insert():
    tk = TestKit()
    tk.must_exec("create sequence ids")
    tk.must_exec("create table st (id int primary key, v varchar(8))")
    tk.must_exec("insert into st values (nextval(ids), 'a')")
    tk.must_exec("insert into st values (nextval(ids), 'b')")
    assert tk.must_query("select id, v from st order by id") == \
        [(1, "a"), (2, "b")]


def test_sequence_exhaustion_and_cycle():
    tk = TestKit()
    tk.must_exec("create sequence small maxvalue 2")
    assert tk.must_query("select nextval(small)") == [(1,)]
    assert tk.must_query("select nextval(small)") == [(2,)]
    with pytest.raises(Exception, match="run out"):
        tk.must_query("select nextval(small)")
    tk.must_exec("create sequence cyc maxvalue 2 cycle")
    vals = [tk.must_query("select nextval(cyc)")[0][0] for _ in range(5)]
    assert vals == [1, 2, 1, 2, 1]


def test_sequence_per_row_contexts_rejected():
    tk = TestKit()
    tk.must_exec("create sequence pr")
    tk.must_exec("create table src (x int)")
    tk.must_exec("insert into src values (1), (2)")
    tk.must_exec("create table dst (id int, x int)")
    with pytest.raises(Exception, match="per-row"):
        tk.must_exec("insert into dst select nextval(pr), x from src")
    with pytest.raises(Exception, match="UPDATE"):
        tk.must_exec("update src set x = nextval(pr)")
    # multi-row VALUES binds each row's call separately: fine
    tk.must_exec("insert into dst values (nextval(pr), 1), "
                 "(nextval(pr), 2)")
    assert tk.must_query("select id from dst order by id") == \
        [(1,), (2,)]


def test_small_sequence_clean_restart_wastes_nothing(tmp_path):
    path = str(tmp_path / "store")
    st = Storage(path)
    s = Session(st)
    s.execute("create sequence sm maxvalue 10")
    assert s.execute("select nextval(sm)").rows == [(1,)]
    st.close()  # checkpoint writes the exact cursor
    st2 = Storage(path)
    s2 = Session(st2)
    assert s2.execute("select nextval(sm)").rows == [(2,)]
    for v in range(3, 11):
        assert s2.execute("select nextval(sm)").rows == [(v,)]
    with pytest.raises(Exception, match="run out"):
        s2.execute("select nextval(sm)")
    st2.close()


def test_round_negative_digits():
    tk = TestKit()
    tk.must_exec("create table rn (d decimal(6,1), i int)")
    tk.must_exec("insert into rn values (44.5, 45), (55.0, 55)")
    rows = tk.must_query(
        "select round(d, 0-1), round(i, 0-1) from rn order by d")
    assert [(str(a), str(b)) for a, b in rows] == \
        [("40", "50"), ("60", "60")]


def test_sequence_survives_restart(tmp_path):
    path = str(tmp_path / "store")
    st = Storage(path)
    s = Session(st)
    s.execute("create sequence rs")
    got = [s.execute("select nextval(rs)").rows[0][0] for _ in range(3)]
    assert got == [1, 2, 3]
    st.close()
    st2 = Storage(path)
    s2 = Session(st2)
    v = s2.execute("select nextval(rs)").rows[0][0]
    # restart skips at most one cache batch, never re-issues
    assert v > 3
    st2.close()


def test_fk_metadata_and_show():
    tk = TestKit()
    tk.must_exec("create table p (id int primary key, u varchar(10))")
    tk.must_exec(
        "create table c (id int primary key, pid int, uu varchar(10), "
        "constraint fk_c foreign key (pid) references p (id) "
        "on delete cascade on update set null, "
        "foreign key (uu) references p (u))")
    info = tk.session.catalog.table("test", "c")
    assert len(info.foreign_keys) == 2
    fk = info.foreign_keys[0]
    assert fk.name == "fk_c" and fk.ref_table == "p" and \
        fk.on_delete == "CASCADE" and fk.on_update == "SET NULL"
    ddl = tk.must_query("show create table c")[0][1]
    assert "FOREIGN KEY (`pid`) REFERENCES `p` (`id`)" in ddl
    assert "ON DELETE CASCADE" in ddl
    # metadata only: inserts are NOT checked (v5.0 reference parity)
    tk.must_exec("insert into c values (1, 999, 'zz')")
    rows = tk.must_query(
        "select constraint_name, referenced_table_name, delete_rule "
        "from information_schema.referential_constraints "
        "order by constraint_name")
    assert rows[0] == ("fk_c", "p", "CASCADE")
    rows = tk.must_query(
        "select column_name, referenced_column_name from "
        "information_schema.key_column_usage "
        "where constraint_name = 'fk_c'")
    assert rows == [("pid", "id")]


def test_fk_column_shorthand():
    tk = TestKit()
    tk.must_exec("create table p2 (id int primary key)")
    tk.must_exec("create table c2 (id int primary key, "
                 "pid int references p2(id))")
    info = tk.session.catalog.table("test", "c2")
    assert len(info.foreign_keys) == 1
    assert info.foreign_keys[0].ref_table == "p2"


def test_mock_owner_serializes_threads():
    import threading
    import time

    m = MockOwnerManager()
    order = []

    def work(tag):
        with m:
            order.append(f"{tag}-in")
            time.sleep(0.05)
            order.append(f"{tag}-out")

    ts = [threading.Thread(target=work, args=(i,)) for i in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    # no interleaving: every -in is immediately followed by its -out
    for i in range(0, 6, 2):
        assert order[i].endswith("-in") and order[i + 1].endswith("-out")
        assert order[i].split("-")[0] == order[i + 1].split("-")[0]


def test_file_lock_owner_mutual_exclusion(tmp_path):
    a = FileLockOwnerManager(str(tmp_path), "ddl")
    b = FileLockOwnerManager(str(tmp_path), "ddl")
    assert a.try_campaign()
    assert not b.try_campaign()  # held by a
    assert b.owner_pid() is not None
    a.resign()
    assert b.try_campaign()
    b.resign()
    a.close()
    b.close()


def test_gc_owner_gates_daemon(tmp_path):
    path = str(tmp_path / "store")
    st = Storage(path)
    s = Session(st)
    s.execute("create table g (a int primary key, b int)")
    s.execute("insert into g values (1, 0)")
    for i in range(1, 5):
        s.execute(f"update g set b = {i} where a = 1")
    s.execute("set global tidb_gc_life_time = '0s'")
    # a foreign holder of the gc lock makes the tick skip GC
    other = FileLockOwnerManager(path, "gc")
    assert other.try_campaign()
    out = st.maintenance.tick()
    assert out["gc_removed"] == 0
    other.resign()
    other.close()
    out = st.maintenance.tick()
    assert out["gc_removed"] >= 3
    st.close()


def test_ddl_runs_under_owner(tmp_path):
    path = str(tmp_path / "store")
    st = Storage(path)
    s = Session(st)
    s.execute("create table d (a int primary key, b int)")
    s.execute("insert into d values (1, 1)")
    s.execute("alter table d add index ib (b)")  # acquires the owner
    info = s.catalog.table("test", "d")
    assert any(ix.name == "ib" for ix in info.indices)
    st.close()
