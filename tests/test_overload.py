"""Server-wide overload protection: admission control, the global
memory governor, and the RPC circuit breaker.

Counterpart of the reference's overload seams (reference:
server/server.go token limiter + ER_CON_COUNT_ERROR 1040;
util/memory's instance-level kill policy; the client-side fail-fast
gates of store/tikv). Fast variants run in tier-1 against mock
trackers and armed failpoints; the real connection-flood and
memory-bomb chaos runs are marked `slow`.
"""

from __future__ import annotations

import os
import socket
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))
from mysql_client import MiniClient, MySQLError  # noqa: E402

from tidb_tpu.rpc.client import RpcClient, RpcOptions  # noqa: E402
from tidb_tpu.rpc.errors import LeaderUnavailable  # noqa: E402
from tidb_tpu.server import Server  # noqa: E402
from tidb_tpu.session import Session, SQLError  # noqa: E402
from tidb_tpu.store.storage import Storage  # noqa: E402
from tidb_tpu.util import failpoint  # noqa: E402
from tidb_tpu.util.governor import (  # noqa: E402
    PRI_DML,
    PRI_POINT,
    PRI_SCAN,
    AdmissionGate,
    AdmissionTimeout,
    MemoryGovernor,
    parse_mem_limit,
)
from tidb_tpu.util.memory import MemTracker  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_failpoints():
    yield
    failpoint.disable_all()


# ==================== mem-limit parsing ====================

def test_parse_mem_limit_forms():
    assert parse_mem_limit(0) == 0
    assert parse_mem_limit("0") == 0
    assert parse_mem_limit("") == 0
    assert parse_mem_limit(None) == 0
    assert parse_mem_limit(1 << 30) == 1 << 30
    assert parse_mem_limit("1073741824") == 1 << 30
    assert parse_mem_limit("50%", total=1000) == 500
    assert parse_mem_limit("0.25", total=1000) == 250
    for bad in ("1.5GB", "-1", "150%", "abc", True, "0.5.1"):
        with pytest.raises(ValueError):
            parse_mem_limit(bad)


# ==================== memory governor (mock trackers) ====================

def _mock_entries(gov, weights, cancellable=None):
    """Register one MemTracker per weight; returns (kill log, tokens)."""
    killed: list[int] = []
    tokens = []
    for i, w in enumerate(weights):
        t = MemTracker(f"q{i}")
        t.consume(w)
        tokens.append(gov.register(
            t, kill=lambda i=i: killed.append(i), label=f"q{i}",
            cancellable=(cancellable[i] if cancellable else True)))
    return killed, tokens


def test_governor_kills_exactly_the_heaviest():
    gov = MemoryGovernor(limit_bytes=1 << 40)  # out of reach for now
    killed, tokens = _mock_entries(gov, [100, 900, 500])
    assert killed == []  # registration under the limit kills nobody
    gov.configure(limit_bytes=1000, cooldown_ms=100)
    with failpoint.failpoint("governor/mem-pressure", 5000):
        assert gov.check() is True
        assert killed == [1]          # the 900-byte statement, only it
        assert gov.check() is False   # cooldown holds
        assert killed == [1]
        gov._last_kill = -1e18        # force the cooldown open
        assert gov.check() is True
        assert killed == [1, 2]       # next heaviest, deterministic
    for tok in tokens:
        gov.unregister(tok)
    assert gov.stats()["statements"] == 0


def test_governor_respects_cancellable_and_pressure():
    gov = MemoryGovernor(limit_bytes=1000, cooldown_ms=0)
    # synthetic pressure BELOW the limit: nothing dies, even at register
    with failpoint.failpoint("governor/mem-pressure", 500):
        killed, tokens = _mock_entries(
            gov, [900, 100], cancellable=[False, True])
        assert killed == []
    # over the limit: the heaviest is NOT cancellable -> the lighter
    # cancellable one dies instead
    with failpoint.failpoint("governor/mem-pressure", 5000):
        assert gov.check() is True
        assert killed == [1]
        # everyone cancellable is already killed: no further victims
        assert gov.check() is False
    for tok in tokens:
        gov.unregister(tok)


def test_governor_disabled_never_kills():
    gov = MemoryGovernor(limit_bytes=0)
    killed, tokens = _mock_entries(gov, [1 << 30])
    with failpoint.failpoint("governor/mem-pressure", 1 << 50):
        assert gov.check() is False
    assert killed == []
    for tok in tokens:
        gov.unregister(tok)


def test_governor_consume_poll_triggers_check():
    """The tracker-consume hot path re-evaluates the ledger every
    GOV_POLL_BYTES of root growth — no background thread involved."""
    gov = MemoryGovernor(limit_bytes=1000, cooldown_ms=0)
    killed: list[str] = []
    root = MemTracker("q")
    with failpoint.failpoint("governor/mem-pressure", 500):
        gov.register(root, kill=lambda: killed.append("q"))
    with failpoint.failpoint("governor/mem-pressure", 5000):
        child = root.child("sort")
        child.consume(8 << 20)  # crosses the poll threshold
    assert killed == ["q"]


def test_governor_kill_end_to_end_typed_8175():
    """A real statement killed by the governor surfaces errno 8175 with
    the server-scoped message, while other sessions keep working, and
    the kill is explainable from the mem_max surfaces afterwards.

    The kill is advisory through the interrupt plane (like KILL QUERY):
    a statement past its last checkpoint completes. The 3-way join is
    sized so the first weight registration happens at the FIRST hash
    build with two more joins plus the aggregate still ahead — plenty
    of checkpoints between the kill and completion.

    Deterministic on 1-core boxes: the limit and the mem-pressure
    failpoint are armed BEFORE the statement starts — no cross-thread
    race against the statement finishing first (the pre-PR-18 flake).
    The registration-time pressure check alone would kill the victim
    at weight 0 (before forensics have anything to record), so it is
    gated out; the kill then fires at the victim's FIRST tracker-
    consume poll, in its own thread, with weight materialized."""
    st = Storage()
    heavy_s = Session(st)
    light_s = Session(st)
    heavy_s.execute("create table s (a int, b varchar(10))")
    rng = np.random.default_rng(3)
    rows = ",".join(f"({int(v)},'k{int(v) % 53}')"
                    for v in rng.integers(0, 100, 4000))
    heavy_s.execute(f"insert into s values {rows}")
    errs: list = []

    def heavy():
        try:
            heavy_s.query("select count(*) from s a "
                          "join s b on a.a = b.a join s c on b.a = c.a")
            errs.append(None)
        except SQLError as e:
            errs.append(e)

    t = threading.Thread(target=heavy)
    # arm BEFORE the statement runs: usage (failpoint) > limit, no
    # timing window. Skip ONLY the registration-time check (it would
    # kill at weight 0); the first consume poll (_gov_next starts at 0)
    # then runs the real check inside the victim's own thread, with
    # the statement's weight materialized.
    st.governor.configure(limit_bytes=1 << 20, cooldown_ms=60_000)
    failpoint.enable("governor/mem-pressure", 2 << 20)
    real_check = st.governor.check
    seen = []

    def gated_check():
        if not seen:
            seen.append(1)
            return False
        return real_check()

    st.governor.check = gated_check
    try:
        t.start()
        t.join(timeout=60)
    finally:
        del st.governor.check
        failpoint.disable("governor/mem-pressure")
        st.governor.configure(limit_bytes=0)
    assert not t.is_alive()
    assert len(errs) == 1 and errs[0] is not None
    assert errs[0].errno == 8175
    assert "[server]" in str(errs[0])
    assert st.governor.kills.get() == 1.0
    # the victim's weight survives for forensics
    assert heavy_s.last_mem_peak > 0
    # the rest of the server is alive and the kill is visible in SQL
    assert light_s.query("select count(*) from s") == [(4000,)]
    mem_rows = light_s.query(
        "select max_mem_bytes from information_schema.statements_summary "
        "where query_sample_text like '%join s c%'")
    assert mem_rows and mem_rows[0][0] > 0


# ==================== admission gate ====================

def test_admission_gate_unlimited_is_noop():
    gate = AdmissionGate()
    assert gate.acquire(PRI_SCAN) is False  # no token held
    with gate.admit(PRI_POINT):
        assert gate.stats()["running"] == 0


def test_admission_timeout_sheds_typed():
    gate = AdmissionGate(tokens=1, timeout_ms=50)
    assert gate.acquire(PRI_SCAN) is True
    t0 = time.monotonic()
    with pytest.raises(AdmissionTimeout) as ei:
        gate.acquire(PRI_SCAN)
    assert time.monotonic() - t0 < 5.0
    assert ei.value.errno == 9003
    assert "busy" in str(ei.value)
    assert gate.stats()["shed"] == 1.0
    assert gate.stats()["queue_depth"] == 0  # waiter cleaned up
    gate.release()
    # the token is reusable after release
    assert gate.acquire(PRI_SCAN) is True
    gate.release()


def test_admission_priority_order():
    """With one token held, a later-arriving high-priority waiter is
    admitted before an earlier low-priority one."""
    gate = AdmissionGate(tokens=1, timeout_ms=10000)
    assert gate.acquire(PRI_SCAN) is True
    order: list[str] = []
    started = threading.Barrier(3)

    def waiter(name, pri):
        started.wait()
        if name == "dml":
            time.sleep(0.2)  # arrives LATER than the scan
        gate.acquire(pri)
        order.append(name)
        gate.release()

    ts = [threading.Thread(target=waiter, args=("scan", PRI_SCAN)),
          threading.Thread(target=waiter, args=("dml", PRI_DML))]
    for t in ts:
        t.start()
    started.wait()
    time.sleep(0.5)  # both queued: scan first, dml second
    gate.release()
    for t in ts:
        t.join(timeout=10)
    assert order == ["dml", "scan"]


def test_admission_end_to_end_shed_errno_9003():
    """token-limit 1: while a heavy statement executes, a concurrent
    SELECT sheds with the typed server-busy error instead of queueing
    forever."""
    st = Storage()
    s1, s2 = Session(st), Session(st)
    s1.execute("create table s (a int, b varchar(10))")
    rng = np.random.default_rng(7)
    rows = ",".join(f"({int(v)},'k{int(v) % 53}')"
                    for v in rng.integers(0, 100, 4000))
    s1.execute(f"insert into s values {rows}")
    st.admission.configure(tokens=1, timeout_ms=200)
    done: list = []

    def heavy():
        done.append(s1.query(
            "select count(*) from s a join s b on a.a = b.a "
            "join s c on b.a = c.a"))

    t = threading.Thread(target=heavy)
    t.start()
    try:
        deadline = time.monotonic() + 30
        while st.admission.stats()["running"] < 1:
            assert time.monotonic() < deadline, "token never acquired"
            time.sleep(0.01)
        with pytest.raises(AdmissionTimeout) as ei:
            s2.query("select count(*) from s")
        assert ei.value.errno == 9003
    finally:
        t.join(timeout=120)
        st.admission.configure(tokens=0)
    assert done and done[0][0][0] >= 4000  # the heavy one completed
    assert st.admission.stats()["shed"] >= 1.0
    # unthrottled again afterwards
    assert s2.query("select count(*) from s") == [(4000,)]


def test_insert_select_does_not_self_deadlock():
    """INSERT .. SELECT re-enters the select path; the admission depth
    guard must not buy a second token at token-limit 1."""
    st = Storage()
    s = Session(st)
    s.execute("create table a (x bigint)")
    s.execute("insert into a values (1),(2),(3)")
    s.execute("create table b (x bigint)")
    st.admission.configure(tokens=1, timeout_ms=500)
    try:
        assert s.execute("insert into b select x from a").affected == 3
        assert s.query("select count(*) from b") == [(3,)]
    finally:
        st.admission.configure(tokens=0)


# ==================== connection gate (errno 1040) ====================

def test_connection_gate_clean_1040():
    srv = Server(port=0, max_connections=2)
    srv.start()
    try:
        c1 = MiniClient("127.0.0.1", srv.port)
        c2 = MiniClient("127.0.0.1", srv.port)
        with pytest.raises(MySQLError) as ei:
            MiniClient("127.0.0.1", srv.port)
        assert ei.value.code == 1040
        assert ei.value.sqlstate == "08004"
        assert srv.storage.obs.conn_rejects.get() == 1.0
        # existing connections keep working through the rejection
        assert c1.query("select 1+1") == [("2",)]
        c1.close()
        # a freed slot admits again
        deadline = time.monotonic() + 10
        while srv.connection_count() >= 2:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        c3 = MiniClient("127.0.0.1", srv.port)
        assert c3.query("select 2+2") == [("4",)]
        c2.close()
        c3.close()
    finally:
        srv.close(drain_timeout=1.0)


@pytest.mark.slow
def test_connection_flood_slow():
    """A flood against a small cap: every attempt either serves queries
    or gets a clean 1040 — no hangs, no leaked sockets (the conftest
    guard enforces the latter)."""
    cap = 8
    srv = Server(port=0, max_connections=cap)
    srv.start()
    results: list[str] = []
    lock = threading.Lock()

    def attempt():
        try:
            c = MiniClient("127.0.0.1", srv.port, timeout=30.0)
            assert c.query("select 40+2") == [("42",)]
            time.sleep(0.2)
            c.close()
            with lock:
                results.append("served")
        except MySQLError as e:
            assert e.code == 1040, e
            with lock:
                results.append("1040")

    try:
        threads = [threading.Thread(target=attempt) for _ in range(40)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not any(t.is_alive() for t in threads), "flood hung"
        assert len(results) == 40
        assert results.count("served") >= cap  # the cap's worth served
        assert results.count("1040") >= 1      # and real shedding
        # drain: every served connection closed cleanly
        deadline = time.monotonic() + 10
        while srv.connection_count() > 0:
            assert time.monotonic() < deadline, "connections leaked"
            time.sleep(0.05)
    finally:
        srv.close(drain_timeout=2.0)


# ==================== wire-level chaos (slow) ====================

@pytest.mark.slow
def test_memory_bomb_wire_slow():
    """Concurrent memory bombs over the wire: the governor kills
    exactly the heaviest (typed 8175) and the light statements
    complete."""
    srv = Server(port=0)
    srv.start()
    st = srv.storage
    try:
        c0 = MiniClient("127.0.0.1", srv.port, timeout=120.0)
        c0.execute("create table s (a int, b varchar(10))")
        rng = np.random.default_rng(11)
        rows = ",".join(f"({int(v)},'k{int(v) % 53}')"
                        for v in rng.integers(0, 200, 8000))
        c0.execute(f"insert into s values {rows}")
        heavy_err: list = []

        def heavy():
            c = MiniClient("127.0.0.1", srv.port, timeout=120.0)
            try:
                c.query("select count(*) from s a join s b "
                        "on a.a = b.a join s c on b.a = c.a")
                heavy_err.append(None)
            except MySQLError as e:
                heavy_err.append(e)
            finally:
                c.close()

        t = threading.Thread(target=heavy)
        t.start()
        deadline = time.monotonic() + 60
        while st.governor.stats()["statements"] < 1:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        st.governor.configure(limit_bytes=1 << 20, cooldown_ms=1000)
        failpoint.enable("governor/mem-pressure", 2 << 20)
        try:
            assert st.governor.check() is True
        finally:
            t.join(timeout=120)
            failpoint.disable("governor/mem-pressure")
            st.governor.configure(limit_bytes=0)
        assert heavy_err and heavy_err[0] is not None
        assert heavy_err[0].code == 8175
        assert heavy_err[0].sqlstate == "HY000"
        # light traffic survives the kill
        assert c0.query("select count(*) from s") == [("8000",)]
        # forensics: the kill shows up in processlist mem columns
        rows = c0.query("select mem_max from "
                        "information_schema.processlist")
        assert rows
        c0.close()
    finally:
        srv.close(drain_timeout=2.0)


# ==================== rpc circuit breaker ====================

def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


BRK_OPTS = dict(connect_timeout_ms=200, request_timeout_ms=1000,
                backoff_budget_ms=200, breaker_threshold=2,
                breaker_cooldown_ms=300)


def test_breaker_trips_and_fails_fast():
    opts = RpcOptions(**BRK_OPTS)
    client = RpcClient(f"127.0.0.1:{_free_port()}", opts,
                       _heartbeat=False)
    try:
        assert client.breaker_state == "closed"
        for _ in range(2):
            with pytest.raises(LeaderUnavailable):
                client.call("ping")
        assert client.breaker_state == "open"
        # open: fail fast WITHOUT burning the backoff budget
        t0 = time.monotonic()
        with pytest.raises(LeaderUnavailable) as ei:
            client.call("ping")
        assert time.monotonic() - t0 < 0.1
        assert "circuit breaker open" in str(ei.value)
        h = client.health()
        assert h["breaker"] == "open"
        assert h["breaker_fail_streak"] == 2
        assert client.degraded
    finally:
        client.close()


def test_breaker_half_open_recovers(tmp_path):
    opts = RpcOptions(**BRK_OPTS)
    leader = Storage(str(tmp_path / "leader"), shared=True,
                     rpc_listen="127.0.0.1:0", rpc_options=opts)
    try:
        client = RpcClient(f"127.0.0.1:{leader.rpc_server.port}", opts,
                           _heartbeat=False)
        try:
            assert client.call("ping") is not None
            # sever the transport deterministically
            failpoint.enable("rpc/conn-drop", True)
            for _ in range(2):
                with pytest.raises(LeaderUnavailable):
                    client.call("ping")
            assert client.breaker_state == "open"
            # heal the wire; the breaker still fails fast mid-cooldown
            failpoint.disable("rpc/conn-drop")
            with pytest.raises(LeaderUnavailable):
                client.call("ping")
            # after the cooldown the half-open probe goes through and
            # recovery closes the breaker
            time.sleep(0.35)
            assert client.breaker_state == "half-open"
            assert client.call("ping") is not None
            assert client.breaker_state == "closed"
            assert not client.degraded
        finally:
            client.close()
    finally:
        leader.close()


def test_breaker_failed_probe_reopens(tmp_path):
    opts = RpcOptions(**BRK_OPTS)
    leader = Storage(str(tmp_path / "leader"), shared=True,
                     rpc_listen="127.0.0.1:0", rpc_options=opts)
    try:
        client = RpcClient(f"127.0.0.1:{leader.rpc_server.port}", opts,
                           _heartbeat=False)
        try:
            failpoint.enable("rpc/conn-drop", True)
            for _ in range(2):
                with pytest.raises(LeaderUnavailable):
                    client.call("ping")
            time.sleep(0.35)
            assert client.breaker_state == "half-open"
            # the probe itself fails: straight back to open
            with pytest.raises(LeaderUnavailable):
                client.call("ping")
            assert client.breaker_state == "open"
            # repoint (e.g. failover) resets the breaker outright
            client.repoint(f"127.0.0.1:{leader.rpc_server.port}")
            assert client.breaker_state == "closed"
            failpoint.disable("rpc/conn-drop")
            assert client.call("ping") is not None
        finally:
            client.close()
    finally:
        leader.close()


def test_breaker_surfaces_in_transport_health(tmp_path):
    opts = RpcOptions(connect_timeout_ms=500, request_timeout_ms=2000,
                      backoff_budget_ms=500, lease_ms=1000,
                      breaker_threshold=2, breaker_cooldown_ms=300)
    leader = Storage(str(tmp_path / "leader"), shared=True,
                     rpc_listen="127.0.0.1:0", rpc_options=opts)
    follower = None
    try:
        follower = Storage(
            str(tmp_path / "follower"),
            remote=f"127.0.0.1:{leader.rpc_server.port}",
            rpc_options=opts)
        h = follower.transport_health()
        assert h["breaker"] == "closed"
        assert "breaker_fail_streak" in h
    finally:
        if follower is not None:
            follower.close()
        leader.close()


# ==================== /status + metrics surfaces ====================

def test_status_exposes_admission_and_governor():
    srv = Server(port=0, status_port=0)
    srv.start()
    try:
        srv.storage.admission.configure(tokens=7, timeout_ms=1234)
        srv.storage.governor.configure(limit_bytes=1 << 30)
        import json
        import urllib.request
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.status_port}/status",
                timeout=10) as r:
            status = json.loads(r.read())
        assert status["admission"]["token_limit"] == 7
        assert status["admission"]["timeout_ms"] == 1234
        assert status["governor"]["limit_bytes"] == 1 << 30
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.status_port}/metrics",
                timeout=10) as r:
            text = r.read().decode()
        assert "tidb_admission_queue_depth" in text
        assert "tidb_governor_memory_usage_bytes" in text
    finally:
        srv.close(drain_timeout=1.0)


def test_cluster_load_carries_admission_governor_rows():
    s = Session()
    s.storage.admission.configure(tokens=4)
    s.query("select 1")
    names = {r[0] for r in s.query(
        "select name from information_schema.cluster_load "
        "where name like 'tidb_admission%' "
        "or name like 'tidb_governor%'")}
    assert "tidb_admission_running" in names
    assert "tidb_governor_memory_usage_bytes" in names


# ==================== satellites ====================

def test_wait_timeout_reaps_idle_connection():
    srv = Server(port=0)
    srv.start()
    try:
        c = MiniClient("127.0.0.1", srv.port)
        c.execute("set session wait_timeout = 1")
        time.sleep(1.6)
        # the server has gone away: the dead socket surfaces as a
        # connection error on the next roundtrip
        with pytest.raises((ConnectionError, OSError)):
            c.query("select 1")
        deadline = time.monotonic() + 10
        while srv.connection_count() > 0:
            assert time.monotonic() < deadline, "reaped conn leaked"
            time.sleep(0.05)
        # an active connection with the default timeout is untouched
        c2 = MiniClient("127.0.0.1", srv.port)
        time.sleep(1.2)
        assert c2.query("select 5") == [("5",)]
        c2.close()
    finally:
        srv.close(drain_timeout=1.0)


def test_kill_denied_1095_without_super():
    srv = Server(port=0, users={"root": ""}, allow_unknown_users=False)
    srv.start()
    try:
        root = MiniClient("127.0.0.1", srv.port)
        root.execute("create user 'bob' identified by 'pw'")
        victim_id = next(iter(srv._conns))  # root's connection id
        bob = MiniClient("127.0.0.1", srv.port, user="bob",
                         password="pw")
        with pytest.raises(MySQLError) as ei:
            bob.execute(f"kill {victim_id}")
        assert ei.value.code == 1095
        assert "not owner" in str(ei.value)
        # root (config account, unchecked) can kill anyone
        bob_id = [cid for cid, c in srv._conns.items()
                  if c.session.user == "bob"][0]
        root.execute(f"kill {bob_id}")
        deadline = time.monotonic() + 10
        while srv.connection_count() > 1:
            assert time.monotonic() < deadline
            time.sleep(0.05)
        root.close()
    finally:
        srv.close(drain_timeout=1.0)


def test_kill_own_user_connection_allowed():
    srv = Server(port=0, users={"root": ""}, allow_unknown_users=False)
    srv.start()
    try:
        root = MiniClient("127.0.0.1", srv.port)
        root.execute("create user 'carol' identified by 'pw'")
        c1 = MiniClient("127.0.0.1", srv.port, user="carol",
                        password="pw")
        c2 = MiniClient("127.0.0.1", srv.port, user="carol",
                        password="pw")
        c1_id = [cid for cid, c in srv._conns.items()
                 if c.session.user == "carol"][0]
        # carol kills her OWN other connection: no SUPER needed
        c2.execute(f"kill {c1_id}")
        deadline = time.monotonic() + 10
        while srv.connection_count() > 2:
            assert time.monotonic() < deadline
            time.sleep(0.05)
        c2.close()
        root.close()
    finally:
        srv.close(drain_timeout=1.0)


def test_overload_plane_leaves_no_threads():
    """Governor + gate are thread-free by design: exercising both must
    not change the thread census."""
    before = {t.ident for t in threading.enumerate()}
    gov = MemoryGovernor(limit_bytes=1000, cooldown_ms=0)
    killed, tokens = _mock_entries(gov, [500])
    with failpoint.failpoint("governor/mem-pressure", 5000):
        gov.check()
    for tok in tokens:
        gov.unregister(tok)
    gate = AdmissionGate(tokens=1, timeout_ms=20)
    assert gate.acquire(PRI_DML) is True
    with pytest.raises(AdmissionTimeout):
        gate.acquire(PRI_SCAN)
    gate.release()
    after = {t.ident for t in threading.enumerate()}
    assert after <= before
