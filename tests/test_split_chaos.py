"""Mid-split kill-9 chaos harness: REAL child processes, REAL exit(9).

The acceptance suite for the online split protocol (rpc/ranged.py
begin_split/_finish_split + kv/rangemeta.py split_spec/table_gaps):
a range-leader child dies by os._exit(9) at each env-armed split
failpoint — range/split-before-meta-commit (journal written, table
uncommitted), range/split-after-meta-commit (table committed, child
WAL empty), range/split-mid-wal-partition (child WAL half-copied),
range/split-before-parent-retire (child ready, parent still holds
both halves) — while concurrent writers straddle the split key.
Invariants asserted against an uncrashed oracle:

  * the keyspace stays gap-free and overlap-free through every crash
    (table_gaps on the recovered meta == []);
  * the half-committed split resolves DETERMINISTICALLY: a death
    before the meta rename rolls back (journal withdrawn, pre-split
    table), any later death rolls forward (successor completes the
    WAL partition and parent retire);
  * every acknowledged write is present exactly once after takeover —
    no failed statements, no doubly-applied statements;
  * repeated kill/recover is idempotent: a SECOND leader killed
    mid-recovery leaves a state a third completes from.

Fast in-process protocol tests live in tests/test_split.py.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from tidb_tpu.kv.mvcc import OP_PUT, Mutation
from tidb_tpu.kv.rangeclient import RangeRouter
from tidb_tpu.kv.rangemeta import table_gaps
from tidb_tpu.kv.tso import TimestampOracle
from tidb_tpu.kv.twopc import Snapshot, TwoPhaseCommitter
from tidb_tpu.rpc.client import RpcClient, RpcOptions
from tidb_tpu.rpc.ranged import RangeDirectory

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SPLIT_KEY = b"\x40"

LEADER_SRC = """
import json, os, signal, sys
sys.path.insert(0, {repo!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
kw = json.loads(os.environ["TIDB_TPU_RANGE_KW"])
from tidb_tpu.kv.rangemeta import split_keyspace
from tidb_tpu.rpc.ranged import RangeServer
srv = RangeServer(kw["root"], lease_ms=kw.get("lease_ms", 500),
                  specs=split_keyspace(kw.get("count", 2)))
print(f"PORT={{srv.address}}", flush=True)
signal.pause()
"""


def _spawn_leader(root: str, lease_ms: int = 500, failpoints: str = "",
                  may_die_in_startup: bool = False):
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "TIDB_TPU_RANGE_KW": json.dumps(
               {"root": root, "lease_ms": lease_ms, "count": 2})}
    env.pop("TIDB_TPU_FAILPOINTS", None)
    if failpoints:
        env["TIDB_TPU_FAILPOINTS"] = failpoints
    proc = subprocess.Popen(
        [sys.executable, "-c", LEADER_SRC.format(repo=REPO)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env)
    deadline = time.time() + 120
    addr = ""
    while time.time() < deadline:
        line = proc.stdout.readline()
        if line.startswith("PORT="):
            addr = line.strip().split("=", 1)[1]
            break
        if proc.poll() is not None:
            if may_die_in_startup:
                # an armed recovery failpoint can fire on the FIRST
                # lease tick, inside the constructor — that death is
                # the scenario, not a harness failure
                return proc, addr
            raise RuntimeError("range leader died during startup")
    assert addr, "leader did not report its address"
    return proc, addr


def _reap(procs):
    for p in procs:
        if p.poll() is None:
            p.kill()
    for p in procs:
        p.wait(timeout=15)
        if p.stdout:
            p.stdout.close()


def _wait_owner(root: str, rid: int, addr: str, timeout_s: float = 30.0):
    d = RangeDirectory(root)
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        g = d.read_grant(rid)
        if g and g.get("owner") == addr \
                and float(g.get("expires_ms", 0)) > time.time() * 1000:
            return g
        time.sleep(0.1)
    raise AssertionError(f"range {rid} never moved to {addr}")


def _wait_split_settled(root: str, want_ranges: int,
                        timeout_s: float = 60.0):
    """Block until the split journal is gone and the table holds
    exactly `want_ranges` gap-free ranges."""
    d = RangeDirectory(root)
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        specs = d.load_specs()
        if d.read_split(1) is None and len(specs) == want_ranges \
                and table_gaps(specs) == []:
            return specs
        time.sleep(0.1)
    specs = d.load_specs()
    raise AssertionError(
        f"split never settled: journal={d.read_split(1)} "
        f"ranges={len(specs)} gaps={table_gaps(specs)}")


def _commit(committer, pairs: dict, tso) -> int:
    muts = [Mutation(OP_PUT, k, v) for k, v in sorted(pairs.items())]
    return committer.commit(muts, tso.ts())


def _fire_split(addr: str, split_key: bytes = SPLIT_KEY):
    """Trigger the operator split RPC; the armed leader dies mid-call,
    so any transport/typed error is expected — the assertions live in
    the recovered on-disk state, not the doomed response."""
    cli = RpcClient(addr, RpcOptions(connect_timeout_ms=2000,
                                     request_timeout_ms=20_000),
                    _heartbeat=False)
    try:
        return cli.call("range_split", range_id=1, split_key=split_key)
    except Exception:  # noqa: BLE001 — death mid-RPC is the point
        return None
    finally:
        cli.close()


STAGES = [
    # (failpoint armed on the leader, ranges after recovery)
    ("range/split-before-meta-commit", 2),   # rolls BACK
    ("range/split-after-meta-commit", 3),    # rolls forward
    ("range/split-mid-wal-partition", 3),    # rolls forward
    ("range/split-before-parent-retire", 3), # rolls forward
]


@pytest.mark.slow
@pytest.mark.parametrize("stage,want_ranges", STAGES)
def test_kill9_mid_split_each_stage(tmp_path, stage, want_ranges):
    """The leader dies by os._exit(9) at each split stage while
    writers hammer both sides of the split key. The standby resolves
    the half-committed split deterministically (back before the meta
    rename, forward after), the keyspace stays gap/overlap-free, and
    every acked write survives exactly once."""
    root = str(tmp_path)
    armed, armed_addr = _spawn_leader(root,
                                      failpoints=f"{stage}=exit(9)@1")
    standby, standby_addr = _spawn_leader(root)
    router = RangeRouter(root=root, budget_ms=60_000)
    acked: dict[bytes, bytes] = {}
    failures: list = []
    stop = threading.Event()
    tso = TimestampOracle()

    def writer(wid: int, prefix: bytes):
        w_router = RangeRouter(root=root, budget_ms=60_000)
        committer = TwoPhaseCommitter(w_router, tso, lock_ttl=2000)
        i = 0
        try:
            while not stop.is_set():
                k = prefix + b"-w%d-%04d" % (wid, i)
                try:
                    _commit(committer, {k: b"v%d" % wid}, tso)
                    acked[k] = b"v%d" % wid
                except Exception as e:  # noqa: BLE001
                    failures.append((k, repr(e)))
                    return
                i += 1
                time.sleep(0.01)
        finally:
            w_router.close()
    # writers straddle the split key: \x10* lands left of \x40, \x60*
    # right of it (both inside pre-split range 1)
    threads = [threading.Thread(target=writer, args=(0, b"\x10")),
               threading.Thread(target=writer, args=(1, b"\x60"))]
    try:
        for rid in (1, 2):
            _wait_owner(root, rid, armed_addr)
        for t in threads:
            t.start()
        time.sleep(0.5)  # let some pre-split acks accumulate
        _fire_split(armed_addr)
        assert armed.wait(timeout=30) == 9, "died AT the failpoint"
        # the standby inherits the parent and resolves the journal
        _wait_owner(root, 1, standby_addr)
        specs = _wait_split_settled(root, want_ranges)
        # writers ride through the crash inside the Backoffer budget
        time.sleep(1.0)
        stop.set()
        for t in threads:
            t.join(timeout=90)
        assert failures == [], f"failed statements: {failures[:3]}"
        assert all(not t.is_alive() for t in threads)
        assert len(acked) > 10, "writers barely ran"
        # exactly-once vs the uncrashed oracle
        snap = Snapshot(router, tso, tso.ts())
        assert dict(snap.scan(b"", b"\x80", -1)) == acked
        # and both sides keep accepting writes post-recovery
        committer = TwoPhaseCommitter(router, tso, lock_ttl=2000)
        _commit(committer, {b"\x10post": b"l", b"\x60post": b"r"}, tso)
        snap = Snapshot(router, tso, tso.ts())
        assert snap.get(b"\x10post") == b"l"
        assert snap.get(b"\x60post") == b"r"
        if want_ranges == 2:
            assert {s.id for s in specs} == {1, 2}
        else:
            assert {s.id for s in specs} == {1, 2, 3}
            by_id = {s.id: s for s in specs}
            assert by_id[1].end_key == SPLIT_KEY
            assert by_id[3].start_key == SPLIT_KEY
            assert by_id[1].epoch == by_id[3].epoch == 2
    finally:
        stop.set()
        for t in threads:
            if t.is_alive():
                t.join(timeout=90)
        router.close()
        _reap([armed, standby])


@pytest.mark.slow
def test_kill9_twice_recovery_is_idempotent(tmp_path):
    """Kill the leader mid-split, then kill the RECOVERING successor
    mid-WAL-partition: a third, unarmed leader still converges to the
    same committed split. Proves _finish_split is an idempotent
    roll-forward, not a one-shot."""
    root = str(tmp_path)
    a, a_addr = _spawn_leader(
        root, failpoints="range/split-after-meta-commit=exit(9)@1")
    router = RangeRouter(root=root, budget_ms=60_000)
    b = c = None
    try:
        tso = TimestampOracle()
        committer = TwoPhaseCommitter(router, tso, lock_ttl=2000)
        for rid in (1, 2):
            _wait_owner(root, rid, a_addr)
        oracle = {}
        for i in range(24):  # both sides of the split key
            k = (b"\x10" if i % 2 else b"\x60") + b"seed%02d" % i
            _commit(committer, {k: b"v%02d" % i}, tso)
            oracle[k] = b"v%02d" % i
        _fire_split(a_addr)
        assert a.wait(timeout=30) == 9
        d = RangeDirectory(root)
        assert len(d.load_specs()) == 3  # meta committed pre-death
        assert d.read_split(1) is not None
        # successor B dies INSIDE recovery, half way through copying
        # the child's WAL
        b, b_addr = _spawn_leader(
            root, failpoints="range/split-mid-wal-partition=exit(9)@1",
            may_die_in_startup=True)
        assert b.wait(timeout=60) == 9, \
            "successor never reached recovery"
        assert d.read_split(1) is not None  # still half-committed
        # third leader, unarmed: recovery completes from any state
        c, c_addr = _spawn_leader(root)
        _wait_owner(root, 1, c_addr)
        specs = _wait_split_settled(root, 3)
        assert {s.id for s in specs} == {1, 2, 3}
        # acked data exactly once, both children serving
        snap = Snapshot(router, tso, tso.ts())
        assert dict(snap.scan(b"", b"\x80", -1)) == oracle
        _commit(committer, {b"\x10fin": b"l", b"\x60fin": b"r"}, tso)
        snap = Snapshot(router, tso, tso.ts())
        assert snap.get(b"\x10fin") == b"l"
        assert snap.get(b"\x60fin") == b"r"
    finally:
        router.close()
        _reap([a] + [p for p in (b, c) if p is not None])
