"""TLS on the MySQL wire (reference: server/server.go:227
LoadTLSCertificates, server/conn.go:665 optional SSLRequest upgrade,
require_secure_transport sysvar semantics)."""

from __future__ import annotations

import pytest

# auto-TLS mints its certificate with the cryptography package; on a
# box without it every fixture here dies in Server.start, so the whole
# module skips (the server itself degrades the same way at runtime)
pytest.importorskip("cryptography")

from mysql_client import MiniClient, MySQLError
from tidb_tpu.server import Server


@pytest.fixture()
def tls_server():
    srv = Server(port=0, auto_tls=True)
    srv.start()
    assert srv.ssl_ctx is not None, "auto-TLS context must build"
    yield srv
    srv.close(drain_timeout=0.2)


def _connect(srv, **kw):
    return MiniClient("127.0.0.1", srv.port, **kw)


def test_tls_handshake_and_queries(tls_server):
    c = _connect(tls_server, use_ssl=True)
    assert c.tls
    assert c.query("select 1 + 1") == [("2",)]
    c.execute("create table t (a int, b varchar(10))")
    c.execute("insert into t values (1, 'enc'), (2, 'rypted')")
    assert c.query("select b from t order by a") == [("enc",), ("rypted",)]
    c.close()


def test_plaintext_still_allowed_by_default(tls_server):
    c = _connect(tls_server, use_ssl=False)
    assert not c.tls
    assert c.query("select 2 + 2") == [("4",)]
    c.close()


def test_tls_with_password_auth(tls_server):
    tls_server.users["alice"] = "secret"
    c = _connect(tls_server, use_ssl=True, user="alice",
                 password="secret")
    assert c.query("select 1") == [("1",)]
    c.close()
    with pytest.raises((MySQLError, ConnectionError)):
        _connect(tls_server, use_ssl=True, user="alice", password="wrong")


def test_require_secure_transport_rejects_plaintext():
    srv = Server(port=0, auto_tls=True, require_secure_transport=True)
    srv.start()
    try:
        with pytest.raises((MySQLError, ConnectionError)) as ei:
            _connect(srv, use_ssl=False)
        if isinstance(ei.value, MySQLError):
            assert ei.value.code == 3159
        c = _connect(srv, use_ssl=True)
        assert c.query("select 5") == [("5",)]
        c.close()
    finally:
        srv.close(drain_timeout=0.2)


def test_set_global_require_secure_transport_takes_effect(tls_server):
    """The enforcement reads the live sysvar, so SET GLOBAL flips it for
    new connections without a restart."""
    c = _connect(tls_server, use_ssl=False)  # plaintext OK initially
    c.execute("set global require_secure_transport = 1")
    with pytest.raises((MySQLError, ConnectionError)):
        _connect(tls_server, use_ssl=False)
    c2 = _connect(tls_server, use_ssl=True)
    c2.execute("set global require_secure_transport = 0")
    c2.close()
    c3 = _connect(tls_server, use_ssl=False)
    assert c3.query("select 7") == [("7",)]
    c3.close()
    c.close()


def test_require_secure_transport_without_tls_refuses_start():
    with pytest.raises(RuntimeError):
        Server(port=0, require_secure_transport=True)


def test_client_against_non_tls_server_fails_cleanly():
    srv = Server(port=0)
    srv.start()
    try:
        assert srv.ssl_ctx is None
        with pytest.raises(MySQLError) as ei:
            _connect(srv, use_ssl=True)
        assert ei.value.code == 2026
        c = _connect(srv)
        assert c.query("select 3") == [("3",)]
        c.close()
    finally:
        srv.close(drain_timeout=0.2)
