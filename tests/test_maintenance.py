"""Failpoint registry + background maintenance loops.

Counterpart of the reference's fault-injected txn tests and GC worker
tests (reference: store/tikv/2pc_fail_test.go via failpoint.Enable;
gcworker/gc_worker_test.go — safepoint vs active transactions;
lock_resolver.go TTL expiry)."""

from __future__ import annotations

import numpy as np
import pytest

from tidb_tpu.kv.mvcc import (KeyIsLockedError, MVCCStore, Mutation,
                              OP_PUT)
from tidb_tpu.kv.region import RegionManager
from tidb_tpu.kv.twopc import Snapshot, TSO, TwoPhaseCommitter
from tidb_tpu.session import Session
from tidb_tpu.store.daemon import MaintenanceWorker, parse_duration
from tidb_tpu.util import failpoint

from testkit import TestKit


class CrashError(Exception):
    pass


@pytest.fixture(autouse=True)
def _clean_failpoints():
    yield
    failpoint.disable_all()


def test_failpoint_registry_basics():
    assert failpoint.inject("nope") is None
    failpoint.enable("p1", 42)
    assert failpoint.inject("p1") == 42
    assert failpoint.hits("p1") == 1
    failpoint.disable("p1")
    assert failpoint.inject("p1") is None
    with failpoint.failpoint("p2", CrashError("boom")):
        with pytest.raises(CrashError):
            failpoint.inject("p2")
    assert failpoint.inject("p2") is None
    calls = []
    failpoint.enable("p3", lambda: calls.append(1))
    failpoint.inject("p3")
    assert calls == [1]


def test_parse_duration():
    assert parse_duration("10m0s") == 600
    assert parse_duration("1h30m") == 5400
    assert parse_duration("500ms") == 0.5
    assert parse_duration("600") == 600
    assert parse_duration("", 123) == 123
    assert parse_duration("junk", 99) == 99


def _kv_fixture():
    store = MVCCStore()
    tso = TSO()
    rm = RegionManager(store)
    committer = TwoPhaseCommitter(rm, tso, lock_ttl=1)
    return store, tso, rm, committer


def test_crash_after_primary_commit_rolls_secondaries_forward():
    """Committed primary + orphaned secondary locks: the resolver must
    roll secondaries FORWARD (reference: 2pc.go:1027 failpoint test)."""
    store, tso, rm, committer = _kv_fixture()
    muts = [Mutation(OP_PUT, b"a", b"v1"), Mutation(OP_PUT, b"b", b"v2")]
    start = tso.ts()
    with failpoint.failpoint("twopc/after-primary-commit", CrashError):
        with pytest.raises(CrashError):
            committer.commit(muts, start)
    # secondary 'b' still locked; a read resolves it from the primary
    locks = store.all_locks()
    assert [l.key for l in locks] == [b"b"]
    snap = Snapshot(rm, tso, tso.ts())
    assert snap.get(b"b") == b"v2"
    assert snap.get(b"a") == b"v1"
    assert store.all_locks() == []


def test_crash_after_prewrite_rolls_back_on_ttl_expiry():
    """Uncommitted prewrite: locks expire by TTL and roll BACK
    (reference: gc_worker resolveLocks phase; lock_resolver TTL)."""
    store, tso, rm, committer = _kv_fixture()
    base = tso.ts()
    committer.commit([Mutation(OP_PUT, b"k", b"old")], base)
    start = tso.ts()
    with failpoint.failpoint("twopc/after-prewrite", CrashError):
        with pytest.raises(CrashError):
            committer.commit([Mutation(OP_PUT, b"k", b"new")], start)
    assert len(store.all_locks()) == 1
    # ttl=1ms: already expired relative to a fresh ts; a reader resolves
    snap = Snapshot(rm, tso, tso.ts())
    assert snap.get(b"k") == b"old"
    assert store.all_locks() == []


def test_maintenance_resolves_expired_locks():
    tk = TestKit()
    s = tk.session
    tk.must_exec("create table t (a int primary key, b int)")
    tk.must_exec("insert into t values (1, 10), (2, 20)")
    # leave an expired orphan lock on the row range via direct prewrite
    storage = s.storage
    from tidb_tpu.kv import codec, tablecodec
    info = s.catalog.table("test", "t")
    key = tablecodec.record_key(info.id, 1)
    start = storage.tso.next_ts()
    storage.kv.prewrite([Mutation(OP_PUT, key, codec.encode_key([1, 99]))],
                        key, start, ttl=0)
    assert len(storage.kv.all_locks()) == 1
    worker = storage.maintenance
    n = worker.resolve_expired_locks()
    assert n == 1 and storage.kv.all_locks() == []
    # the uncommitted write must NOT be visible
    assert tk.must_query("select b from t where a = 1") == [(10,)]


def test_gc_reclaims_versions_protects_active_snapshots():
    tk = TestKit()
    s = tk.session
    storage = s.storage
    tk.must_exec("create table g (a int primary key, b int)")
    tk.must_exec("insert into g values (1, 0)")
    # hold a snapshot over the first version
    held = storage.begin()
    from tidb_tpu.kv import tablecodec
    info = s.catalog.table("test", "g")
    key = tablecodec.record_key(info.id, 1)
    v0 = storage.kv.get(key, held.start_ts)
    assert v0 is not None
    for i in range(1, 6):
        tk.must_exec(f"update g set b = {i} where a = 1")
    tk.must_exec("set global tidb_gc_life_time = '0s'")
    worker = storage.maintenance
    removed = worker.run_gc()
    # versions newer than the held snapshot are protected; the held
    # snapshot still reads its version
    assert storage.kv.get(key, held.start_ts) == v0
    assert tk.must_query("select b from g where a = 1") == [(5,)]
    held.rollback()  # releases the snapshot ts
    removed2 = worker.run_gc()
    assert removed + removed2 >= 4  # old versions reclaimed after release
    assert tk.must_query("select b from g where a = 1") == [(5,)]


def test_gc_never_drops_newest_version():
    tk = TestKit()
    storage = tk.session.storage
    tk.must_exec("create table n (a int primary key, b int)")
    tk.must_exec("insert into n values (1, 1), (2, 2)")
    tk.must_exec("delete from n where a = 2")
    tk.must_exec("set global tidb_gc_life_time = '0s'")
    storage.maintenance.tick()
    assert tk.must_query("select a, b from n order by a") == [(1, 1)]
    # deleted key's tombstone history is fully reclaimable
    tk.must_exec("insert into n values (2, 22)")
    assert tk.must_query("select b from n where a = 2") == [(22,)]


def test_auto_analyze_via_maintenance_tick():
    tk = TestKit()
    storage = tk.session.storage
    tk.must_exec("create table aa (a int, b int)")
    rows = ",".join(f"({i},{i % 7})" for i in range(2000))
    tk.must_exec(f"insert into aa values {rows}")
    out = storage.maintenance.tick()
    assert "aa" in out["auto_analyzed"]
    st = storage.stats.table_stats(tk.session.catalog.table("test", "aa").id)
    assert st is not None


def test_ddl_crash_between_steps_resumes():
    """Owner crash mid-ADD-INDEX via the registry; a new worker resumes
    from the persisted job queue (reference: ddl_worker crash tests)."""
    from tidb_tpu.ddl import DDL, DDLError

    tk = TestKit()
    s = tk.session
    tk.must_exec("create table d (a int primary key, b int)")
    rows = ",".join(f"({i},{i % 50})" for i in range(500))
    tk.must_exec(f"insert into d values {rows}")

    crashes = {"n": 0}

    def crash_on_third():
        crashes["n"] += 1
        if crashes["n"] == 3:
            raise CrashError("owner died")

    failpoint.enable("ddl/before-step", crash_on_third)
    with pytest.raises(CrashError):
        tk.must_exec("alter table d add index ib (b)")
    failpoint.disable("ddl/before-step")
    assert s.storage.ddl_jobs  # job still queued with its checkpoint
    ddl = DDL(s.storage, s.catalog)
    ddl.resume_pending()
    assert not s.storage.ddl_jobs
    info = s.catalog.table("test", "d")
    assert any(ix.name == "ib" for ix in info.indices)
    assert tk.must_query("select count(*) from d where b = 7") == [(10,)]


def test_storage_before_fold_failpoint_counts():
    tk = TestKit()
    tk.must_exec("create table f (a int primary key)")
    failpoint.enable("storage/before-fold")
    tk.must_exec("insert into f values (1)")
    assert failpoint.hits("storage/before-fold") == 1


def test_maintenance_thread_lifecycle():
    tk = TestKit()
    storage = tk.session.storage
    worker = storage.maintenance
    worker.start(interval_s=0.05)
    tk.must_exec("create table z (a int primary key, b int)")
    tk.must_exec("insert into z values (1, 1)")
    import time

    time.sleep(0.2)
    worker.stop()
    assert worker._thread is None
