"""Cross-commit group fsync (ISSUE 12): the SyncPolicy rendezvous,
storage-level amortization, durability parity with the per-commit
fsync it replaced, and the telemetry surfaces.

The kill-9 halves live with the rest of the torture harness in
tests/test_failover.py (slow-marked)."""

import os
import threading
import time

import pytest

from tidb_tpu.kv.mvcc import SyncPolicy
from tidb_tpu.session import Session
from tidb_tpu.store.storage import Storage


# ---------------------------------------------------------------------------
# SyncPolicy rendezvous unit tests
# ---------------------------------------------------------------------------

def _group_policy(fsync):
    sp = SyncPolicy("commit", 100, fsync)
    sp.defer_commit = True
    return sp


def test_rendezvous_amortizes_concurrent_commits():
    calls = []

    def slow_fsync():
        time.sleep(0.02)
        calls.append(1)

    sp = _group_policy(slow_fsync)
    batches = []
    sp.on_batch = batches.append

    def commit():
        sp.mark_dirty()
        sp.commit_sync()

    threads = [threading.Thread(target=commit) for _ in range(32)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(calls) <= 6, f"32 commits cost {len(calls)} fsyncs"
    # every commit was covered by SOME batch's fsync
    assert sum(batches) >= 32 - len(batches)


def test_boundary_defers_but_commit_sync_is_required():
    calls = []
    sp = _group_policy(lambda: calls.append(1))
    sp.mark_dirty()
    sp.boundary()          # deferred: no fsync inside the section
    assert calls == []
    sp.commit_sync()       # the ack path pays it
    assert calls == [1]
    sp.commit_sync()       # already covered: no second fsync
    assert calls == [1]


def test_non_deferred_commit_policy_unchanged():
    """A bare SyncPolicy (defer_commit False) keeps the historical
    fsync-per-boundary behavior — the shared-dir/flock mode contract."""
    calls = []
    sp = SyncPolicy("commit", 100, lambda: calls.append(1))
    sp.mark_dirty()
    sp.boundary()
    assert calls == [1]
    sp.commit_sync()  # boundary already covered this write generation
    assert calls == [1]


def test_fsync_failure_propagates_and_stranded_waiters_retry():
    fail_once = [True]
    ok_calls = []

    def flaky():
        if fail_once[0]:
            fail_once[0] = False
            raise OSError("disk gone")
        ok_calls.append(1)

    sp = _group_policy(flaky)
    errs = []

    def commit():
        sp.mark_dirty()
        try:
            sp.commit_sync()
        except OSError as e:
            errs.append(e)

    threads = [threading.Thread(target=commit) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # exactly the leader that hit the bad fsync fails; the stranded
    # waiters elected a new leader and are durable
    assert len(errs) == 1
    assert ok_calls, "no retry fsync ran"
    sp.mark_dirty()
    sp.commit_sync()  # and the policy stays usable


def test_leader_gather_window_and_max_batch():
    calls = []
    sp = _group_policy(lambda: calls.append(1))
    sp.group_max_wait_us = 20000
    sp.group_max_batch = 2
    t0 = time.perf_counter()
    sp.mark_dirty()
    sp.commit_sync()
    dt = time.perf_counter() - t0
    assert dt >= 0.015, f"gather window skipped ({dt * 1e3:.1f}ms)"
    assert calls == [1]


# ---------------------------------------------------------------------------
# storage-level behavior
# ---------------------------------------------------------------------------

def _count_wal_fsyncs(st) -> list:
    """Monkeypatch the engine's fsync callable to count invocations."""
    eng = st.kv.kv
    calls = []
    inner = eng._syncer._fsync

    def counting():
        calls.append(1)
        inner()
    eng._syncer._fsync = counting
    return calls


def test_concurrent_commits_share_fsyncs(tmp_path):
    st = Storage(str(tmp_path / "db"), sync_log="commit")
    s0 = Session(st)
    s0.execute("create table g (id bigint primary key, v bigint)")
    for i in range(64):
        s0.execute(f"insert into g values ({i}, 0)")
    calls = _count_wal_fsyncs(st)
    _, sum0, n0 = st.obs.group_commit_batch.snapshot()
    n_threads, per = 8, 6
    errs = []

    def work(wi: int) -> None:
        try:
            s = Session(st)
            for j in range(per):
                s.execute(f"update g set v = v + 1 "
                          f"where id = {wi * per + j}")
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=work, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs
    commits = n_threads * per
    assert len(calls) < commits, \
        f"{commits} durable commits cost {len(calls)} fsyncs (no " \
        "amortization)"
    # the histogram saw the same story
    _, sum1, n1 = st.obs.group_commit_batch.snapshot()
    assert sum1 - sum0 >= commits  # every commit counted into a batch
    assert n1 - n0 <= len(calls)
    # and every write is present
    assert Session(st).query("select sum(v) from g")[0][0] == commits
    st.close()


def test_durability_parity_after_crash(tmp_path):
    """Group commit keeps the sync-log=commit contract: every ACKED
    commit survives a process crash (close() without checkpoint)."""
    p = str(tmp_path / "db")
    st = Storage(p, sync_log="commit")
    s = Session(st)
    s.execute("create table d (id bigint primary key, v bigint)")
    acked = []
    for i in range(20):
        s.execute(f"insert into d values ({i}, {i})")
        acked.append(i)
    # crash: drop the storage without checkpoint/flush
    st.kv.kv.close()
    st2 = Storage(p)
    got = {r[0] for r in Session(st2).query("select id from d")}
    assert set(acked) <= got
    st2.close()


def test_group_commit_event_and_knobs(tmp_path):
    st = Storage(str(tmp_path / "db"), sync_log="commit")
    st.configure_group_commit(max_batch=16, max_wait_us=500)
    syncer = st.kv.kv._syncer
    assert syncer.group_max_batch == 16
    assert syncer.group_max_wait_us == 500
    # a multi-commit batch emits a throttled group_commit event
    st._note_group_commit(4)
    kinds = {e["kind"] for e in st.obs.events.snapshot()}
    assert "group_commit" in kinds
    st.close()


def test_wire_path_off_mode_untouched(tmp_path):
    """sync-log=off stores never fsync at commit (commit_sync no-op)."""
    st = Storage(str(tmp_path / "db"), sync_log="off")
    calls = _count_wal_fsyncs(st)
    s = Session(st)
    s.execute("create table o (id bigint primary key)")
    for i in range(5):
        s.execute(f"insert into o values ({i})")
    assert calls == []
    st.close()


def test_amortization_factor_grows_with_writers(tmp_path):
    """The acceptance shape at test scale, measured by the load-
    insensitive invariant: the commits-per-fsync factor at 8 writers
    beats the single-writer 1.0 (wall-clock QPS is the bench flight's
    number — a contended CI core makes it unusable here)."""
    st = Storage(str(tmp_path / "db"), sync_log="commit")
    # make the fsync expensive enough to dominate (CI tmpfs fsyncs in
    # microseconds and writers would outrun the rendezvous window)
    eng = st.kv.kv
    inner = eng._syncer._fsync

    def padded():
        inner()
        time.sleep(0.004)
    eng._syncer._fsync = padded
    s0 = Session(st)
    s0.execute("create table t (id bigint primary key, v bigint)")
    for i in range(256):
        s0.execute(f"insert into t values ({i}, 0)")

    def factor(conc: int, per: int = 12) -> float:
        _, sum0, n0 = st.obs.group_commit_batch.snapshot()

        def w(wi: int) -> None:
            s = Session(st)
            for j in range(per):
                s.execute(f"update t set v = v + 1 "
                          f"where id = {(wi * 29 + j) % 256}")
        threads = [threading.Thread(target=w, args=(i,))
                   for i in range(conc)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        _, sum1, n1 = st.obs.group_commit_batch.snapshot()
        return (sum1 - sum0) / max(n1 - n0, 1)

    f1 = factor(1)
    f8 = factor(8)
    assert f1 <= 1.5, f"single writer should not batch ({f1:.2f})"
    assert f8 > 1.3, f"no fsync amortization at 8 writers ({f8:.2f})"
    st.close()
