"""Network chaos over the range plane: partition-tolerant reads.

The acceptance suite for PR 20's fault-injection seam (rpc/netfault.py
hooked into rpc/frame.py) and the per-range closed-timestamp ledger
(rpc/ranged.py): concurrent cross-range transfers run under
delay/drop/dup schedules and partition/heal cycles, with a split and a
leader handover mid-storm, and a history checker asserts

  * per-range closed timestamps NEVER regress (monotonic through
    splits, transfers, and partitions);
  * snapshots at a covered timestamp are prefix-consistent — no torn
    cross-range transaction is ever observable at or below the min
    closed_ts of the ranges it touched;
  * every acknowledged transfer is durable exactly once (balance
    invariant against an uncrashed oracle);
  * the unarmed frame path does zero fault-plane work (the WORK
    poison pin).

Unit coverage for the fault engine itself (schedule matching,
determinism, asymmetric partitions) lives here too, driven through
real socketpairs and the real send_frame/recv_frame.
"""

from __future__ import annotations

import socket
import threading
import time

import pytest

from tidb_tpu.kv.mvcc import OP_PUT, Mutation
from tidb_tpu.kv.rangeclient import RangeRouter
from tidb_tpu.kv.rangemeta import split_keyspace
from tidb_tpu.kv.tso import TimestampOracle
from tidb_tpu.kv.twopc import Snapshot, TwoPhaseCommitter
from tidb_tpu.rpc import netfault
from tidb_tpu.rpc.client import RpcOptions
from tidb_tpu.rpc.frame import recv_frame, send_frame
from tidb_tpu.rpc.ranged import RangeServer
from tidb_tpu.util import failpoint

# short transport timeouts: a silently-dropped frame must resolve into
# a retry in ~250ms, not the 5s production default
OPTS = RpcOptions(connect_timeout_ms=500, request_timeout_ms=400,
                  backoff_budget_ms=6000)


@pytest.fixture(autouse=True)
def _clean():
    netfault.reset()
    yield
    failpoint.disable_all()
    netfault.reset()


# ==================== the fault engine, at the socket ====================

def _pair():
    a, b = socket.socketpair()
    a.settimeout(2.0)
    b.settimeout(2.0)
    return a, b


def test_unarmed_frames_do_zero_fault_work():
    """The zero-work contract: with nothing armed the frame path reads
    netfault.ACTIVE and nothing else — the WORK pin stays flat."""
    a, b = _pair()
    try:
        assert netfault.ACTIVE is False
        before = netfault.WORK
        for i in range(50):
            send_frame(a, b"x%d" % i)
            assert recv_frame(b) == b"x%d" % i
        assert netfault.WORK == before
    finally:
        a.close()
        b.close()


def test_delay_drop_dup_partition_schedules():
    a, b = _pair()
    try:
        # delay: a fixed sleep on matching frames
        netfault.arm("net/delay", ms=30)
        t0 = time.perf_counter()
        send_frame(a, b"slow")
        assert recv_frame(b) == b"slow"
        assert time.perf_counter() - t0 >= 0.03
        netfault.heal("net/delay")

        # drop: deterministic — every 3rd frame vanishes (send side)
        netfault.arm("net/drop", nth=3)
        got = []
        for i in range(6):
            send_frame(a, b"d%d" % i)
        b.settimeout(0.2)
        with pytest.raises((socket.timeout, ConnectionError)):
            while True:
                got.append(recv_frame(b))
        assert got == [b"d0", b"d1", b"d3", b"d4"]  # d2, d5 dropped
        netfault.heal("net/drop")

        # dup: every frame doubled; both copies arrive intact
        netfault.arm("net/dup")
        send_frame(a, b"twice")
        netfault.heal("net/dup")
        b.settimeout(2.0)
        assert recv_frame(b) == b"twice"
        assert recv_frame(b) == b"twice"

        # partition: the wire is cut typed, heal restores it
        netfault.arm("net/partition")
        with pytest.raises(ConnectionResetError):
            send_frame(a, b"cut")
        netfault.heal("net/partition")
        send_frame(a, b"healed")
        assert recv_frame(b) == b"healed"
        assert netfault.WORK > 0  # the armed path did count entries
    finally:
        a.close()
        b.close()


def test_peer_matching_and_asymmetric_partition():
    """side+dir express asymmetric cuts: traffic TOWARD the named
    endpoint dies while the reverse direction still flows."""
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]
    cli = socket.create_connection(("127.0.0.1", port), timeout=2.0)
    acc, _ = srv.accept()
    cli.settimeout(2.0)
    acc.settimeout(2.0)
    try:
        # a rule naming some OTHER endpoint must not fire here
        netfault.arm("net/partition", peer="127.0.0.1:1", side="peer")
        send_frame(cli, b"pass")
        assert recv_frame(acc) == b"pass"
        netfault.heal("net/partition")

        # cut only frames SENT TOWARD the server's port: the client's
        # send dies, the server can still push toward the client
        netfault.arm("net/partition", peer=f"127.0.0.1:{port}",
                     side="peer", dir="send")
        with pytest.raises(ConnectionResetError):
            send_frame(cli, b"toward-server")
        send_frame(acc, b"from-server")  # acc's peer is the CLIENT
        assert recv_frame(cli) == b"from-server"
    finally:
        cli.close()
        acc.close()
        srv.close()


# ==================== the chaos harness ====================

def _acct_key(i: int) -> bytes:
    # accounts spread across the 4-way split at g/n/t
    return b"acct/%c%03d" % (b"afpu"[i % 4], i)


def _read_accounts(router, tso, n, read_ts=None):
    snap = Snapshot(router, tso,
                    read_ts if read_ts is not None else tso.ts())
    out = {}
    for i in range(n):
        v = snap.get(_acct_key(i))
        out[i] = int(v) if v else 0
    return out


def test_transfers_survive_partition_heal_cycles(tmp_path):
    """The headline drill: concurrent cross-range transfers while the
    wire degrades (delay+dup armed throughout, drop and full-partition
    phases cycling), a split lands mid-storm, and write leadership
    changes hands. The oracle is the sum of acknowledged transfers."""
    root = str(tmp_path / "ranges")
    n_accts, seed = 8, 100
    srv = RangeServer(root, lease_ms=400,
                      specs=split_keyspace(4, (b"acct/g", b"acct/p",
                                               b"acct/u")))
    tso = TimestampOracle()
    router = RangeRouter(root=root, options=OPTS, budget_ms=12000)
    seed_c = TwoPhaseCommitter(router, tso, lock_ttl=2000)
    seed_c.commit([Mutation(OP_PUT, _acct_key(i), b"%d" % seed)
                   for i in range(n_accts)], tso.ts())

    stop = threading.Event()
    acked = []          # (start_ts, commit_ts, src, dst, amt)
    closed_floor: dict[int, int] = {}
    errors: list[str] = []

    def transfer_worker(wid: int) -> None:
        c = TwoPhaseCommitter(router, tso, lock_ttl=2000,
                              max_retries=30)
        k = 0
        while not stop.is_set():
            src = (wid * 3 + k) % n_accts
            dst = (src + 1) % n_accts  # adjacent = different range
            k += 1
            ts = tso.ts()
            snap = Snapshot(router, tso, ts)
            try:
                a = int(snap.get(_acct_key(src)) or b"0")
                b_ = int(snap.get(_acct_key(dst)) or b"0")
                if a < 1:
                    continue
                cts = c.commit(
                    [Mutation(OP_PUT, _acct_key(src), b"%d" % (a - 1)),
                     Mutation(OP_PUT, _acct_key(dst), b"%d" % (b_ + 1))],
                    ts)
                acked.append((ts, cts, src, dst, 1))
            except Exception:  # noqa: BLE001 — conflicts/cuts retry
                continue

    def closed_monitor() -> None:
        while not stop.is_set():
            try:
                for rid, ts in router.closed_over(b"", b"",
                                                  refresh=True):
                    prev = closed_floor.get(rid, 0)
                    if ts < prev:
                        errors.append(
                            f"closed_ts regressed on r{rid}: "
                            f"{prev} -> {ts}")
                    closed_floor[rid] = max(prev, ts)
            except Exception:  # noqa: BLE001 — mid-handover read
                pass
            time.sleep(0.05)

    def prefix_reader() -> None:
        # no torn cross-range txn at a covered timestamp: both legs of
        # an acked transfer land in the same snapshot prefix, so the
        # total at ANY covered ts equals the seeded total
        while not stop.is_set():
            time.sleep(0.15)
            try:
                cov = min(ts for _, ts in
                          router.closed_over(b"", b"", refresh=True))
                if cov <= 0:
                    continue
                snap = Snapshot(router, tso, cov)
                total = sum(
                    int(snap.get(_acct_key(i)) or b"0")
                    for i in range(n_accts))
                if total != n_accts * seed:
                    errors.append(
                        f"torn snapshot at covered ts {cov}: "
                        f"total {total} != {n_accts * seed}")
            except Exception:  # noqa: BLE001 — cut mid-scan retries
                continue

    threads = [threading.Thread(target=transfer_worker, args=(w,),
                                daemon=True) for w in range(3)]
    threads += [threading.Thread(target=closed_monitor, daemon=True),
                threading.Thread(target=prefix_reader, daemon=True)]
    srv2 = None
    try:
        # background degradation for the whole run
        netfault.arm("net/delay", ms=2)
        netfault.arm("net/dup", nth=5)
        for t in threads:
            t.start()

        # phase 1: loss
        netfault.arm("net/drop", nth=7)
        time.sleep(0.6)
        netfault.heal("net/drop")

        # phase 2: full partition of the range tier, then heal
        netfault.arm("net/partition", peer=srv.address, side="peer")
        time.sleep(0.4)
        netfault.heal("net/partition")
        time.sleep(0.4)

        # phase 3: a split lands mid-storm (ledger handoff to children)
        parent = next(h.id for h in router.regions()
                      if h.contains(b"acct/a000"))
        srv.split_range(parent, b"acct/c")
        time.sleep(0.4)

        # phase 4: leadership changes hands — srv dies unreleased, a
        # successor process takes over after the lease horizon; the
        # published closed floors must carry across the transfer
        netfault.arm("net/partition", peer=srv.address, side="peer")
        time.sleep(0.3)
        netfault.heal("net/partition")
        srv.close(release=False)
        srv2 = RangeServer(root, lease_ms=400)
        time.sleep(1.2)

        stop.set()
        for t in threads:
            t.join(timeout=30)
        assert not any(t.is_alive() for t in threads)
        netfault.heal()

        assert errors == [], errors[:5]
        assert acked, "no transfer ever succeeded under chaos"

        # the history check against the uncrashed oracle: balances
        # reflect the acked transfers EXACTLY (exactly-once, no loss)
        deadline = time.time() + 10
        while True:
            final = _read_accounts(router, tso, n_accts)
            want = {i: seed for i in range(n_accts)}
            for _, _, src, dst, amt in acked:
                want[src] -= amt
                want[dst] += amt
            if final == want:
                break
            if time.time() > deadline:
                assert final == want
            time.sleep(0.2)

        # closed floors are live again and cover fresh timestamps
        cov_deadline = time.time() + 8
        while True:
            cov = min(ts for _, ts in
                      router.closed_over(b"", b"", refresh=True))
            if cov >= max(cts for _, cts, *_ in acked):
                break
            assert time.time() < cov_deadline, \
                "closed_ts never covered the last acked commit"
            time.sleep(0.1)
    finally:
        stop.set()
        netfault.heal()
        router.close()
        if srv2 is not None:
            srv2.close()
        else:
            srv.close()


# ==================== ledger semantics, in process ====================

def test_cross_range_commit_holds_ledger_until_txn_done(tmp_path):
    """A cross-range participant's closed_ts stays BELOW the txn's
    commit_ts until the coordinator's txn_done reports every secondary
    durable — the window where a replica read could otherwise observe
    a torn transaction."""
    root = str(tmp_path / "r")
    srv = RangeServer(root, lease_ms=300, specs=split_keyspace(2, (b"m",)))
    tso = TimestampOracle()
    router = RangeRouter(root=root, options=OPTS)
    try:
        left = router.locate(b"a")
        right = router.locate(b"z")
        assert left.id != right.id
        start_ts = tso.ts()
        router.prewrite(left, [Mutation(OP_PUT, b"a", b"1")], b"a",
                        start_ts)
        router.prewrite(right, [Mutation(OP_PUT, b"z", b"1")], b"a",
                        start_ts)
        commit_ts = tso.ts()
        # primary committed, done=False: the ledger entry re-pins at
        # commit_ts instead of retiring
        router.commit(left, [b"a"], start_ts, commit_ts, done=False)
        time.sleep(0.5)  # heartbeats publish while the hold is open
        closed = dict(router.closed_over(b"", b"", refresh=True))
        assert closed[left.id] < commit_ts, \
            "participant closed past an in-flight cross-range commit"
        # secondary durable + txn_done: both ranges may now advance
        router.commit(right, [b"z"], start_ts, commit_ts, done=False)
        router.txn_done(left, start_ts)
        router.txn_done(right, start_ts)
        deadline = time.time() + 5
        while True:
            closed = dict(router.closed_over(b"", b"", refresh=True))
            if min(closed.values()) >= commit_ts:
                break
            assert time.time() < deadline, closed
            time.sleep(0.05)
    finally:
        router.close()
        srv.close()


def test_lost_txn_done_self_retires_after_hold_ttl(tmp_path):
    """A crashed coordinator never sends txn_done: the commit-pinned
    ledger entry expires after hold_ms and closed_ts moves on (any
    still-unresolved secondary lock keeps pinning via the lock union,
    so the early retire is safe)."""
    root = str(tmp_path / "r")
    srv = RangeServer(root, lease_ms=200,
                      specs=split_keyspace(2, (b"m",)), hold_ms=400)
    tso = TimestampOracle()
    router = RangeRouter(root=root, options=OPTS)
    try:
        left = router.locate(b"a")
        start_ts = tso.ts()
        router.prewrite(left, [Mutation(OP_PUT, b"a", b"1")], b"a",
                        start_ts)
        commit_ts = tso.ts()
        router.commit(left, [b"a"], start_ts, commit_ts, done=False)
        # no txn_done — the hold must expire on its own
        deadline = time.time() + 6
        while True:
            closed = dict(router.closed_over(b"", b"",
                                             refresh=True))[left.id]
            if closed >= commit_ts:
                break
            assert time.time() < deadline, \
                "ledger hold never expired without txn_done"
            time.sleep(0.05)
    finally:
        router.close()
        srv.close()


def test_leader_transfer_floors_successor_closed_ts(tmp_path):
    """The successor's published closed_ts starts AT OR ABOVE the
    predecessor's last published value (the monotonicity half of the
    closed-timestamp contract across failover)."""
    root = str(tmp_path / "r")
    srv = RangeServer(root, lease_ms=250, specs=split_keyspace(1))
    tso = TimestampOracle()
    router = RangeRouter(root=root, options=OPTS)
    try:
        c = TwoPhaseCommitter(router, tso)
        c.commit([Mutation(OP_PUT, b"k", b"v")], tso.ts())
        time.sleep(0.6)  # a few heartbeat publications
        before = dict(router.closed_over(b"", b"", refresh=True))
        assert min(before.values()) > 0
        srv.close(release=False)  # die without releasing = kill
        srv2 = RangeServer(root, lease_ms=250)
        try:
            deadline = time.time() + 8
            while not srv2.hosted_ids():
                assert time.time() < deadline, "successor never led"
                time.sleep(0.05)
            after = dict(router.closed_over(b"", b"", refresh=True))
            for rid, floor in before.items():
                assert after[rid] >= floor, \
                    f"r{rid} closed_ts regressed across transfer"
        finally:
            srv2.close()
    finally:
        router.close()


def test_split_hands_ledger_floor_to_children(tmp_path):
    """Both split children start with closed_ts >= the parent's value
    at the handoff point, and an in-flight cross-range txn spanning
    the split key keeps BOTH children below its commit_ts."""
    root = str(tmp_path / "r")
    srv = RangeServer(root, lease_ms=300, specs=split_keyspace(1))
    tso = TimestampOracle()
    router = RangeRouter(root=root, options=OPTS)
    try:
        c = TwoPhaseCommitter(router, tso)
        c.commit([Mutation(OP_PUT, b"a", b"1"),
                  Mutation(OP_PUT, b"z", b"1")], tso.ts())
        parent = router.regions()[0]
        # an open cross-range-style hold on the parent (commit pinned,
        # no txn_done yet) straddling the future split point
        start_ts = tso.ts()
        router.prewrite(parent, [Mutation(OP_PUT, b"b", b"2"),
                                 Mutation(OP_PUT, b"y", b"2")], b"b",
                        start_ts)
        commit_ts = tso.ts()
        router.commit(parent, [b"b", b"y"], start_ts, commit_ts,
                      done=False)
        parent_closed = dict(router.closed_over(
            b"", b"", refresh=True))[parent.id]
        srv.split_range(parent.id, b"m")
        closed = dict(router.closed_over(b"", b"", refresh=True))
        assert len(closed) == 2
        for rid, ts in closed.items():
            assert ts >= parent_closed, \
                f"child r{rid} below the parent's handoff floor"
            assert ts < commit_ts, \
                f"child r{rid} closed past the straddling txn"
        router.txn_done(router.locate(b"b"), start_ts)
        router.txn_done(router.locate(b"y"), start_ts)
        deadline = time.time() + 6
        while True:
            closed = dict(router.closed_over(b"", b"", refresh=True))
            if min(closed.values()) >= commit_ts:
                break
            assert time.time() < deadline, closed
            time.sleep(0.05)
    finally:
        router.close()
        srv.close()
