"""Online DDL: ALTER TABLE, CREATE/DROP INDEX, job states, resumable reorg.

Mirrors the reference's ddl tests (ddl/db_test.go add-index/add-column
surface, ddl/reorg.go checkpoint resume, ddl/rollingback.go error paths).
"""

import pytest

from tidb_tpu.ddl import DDL, DDLError
from tidb_tpu.session import Session, SQLError


@pytest.fixture
def se():
    s = Session()
    s.execute("CREATE TABLE t (id INT PRIMARY KEY, a INT, b VARCHAR(10))")
    s.execute("INSERT INTO t VALUES (1,10,'x'),(2,20,'y'),(3,30,'z')")
    return s


# ---------------- ADD / DROP INDEX ----------------

def test_create_index_and_use(se):
    se.execute("CREATE INDEX ka ON t (a)")
    info = se.catalog.table("test", "t")
    assert any(ix.name == "ka" and ix.visible for ix in info.indices)
    se.execute("ANALYZE TABLE t")
    assert se.query("SELECT id FROM t WHERE a = 20") == [(2,)]


def test_create_unique_index_validates(se):
    se.execute("INSERT INTO t VALUES (4,10,'w')")  # duplicate a=10
    with pytest.raises(SQLError, match="Duplicate entry '10'"):
        se.execute("CREATE UNIQUE INDEX ua ON t (a)")
    # rolled back: no index left behind
    info = se.catalog.table("test", "t")
    assert not any(ix.name == "ua" for ix in info.indices)
    jobs = se.query("ADMIN SHOW DDL JOBS")
    assert jobs[0][5] == "rolled back"


def test_unique_index_then_enforced(se):
    se.execute("ALTER TABLE t ADD UNIQUE KEY ua (a)")
    with pytest.raises(SQLError, match="Duplicate entry"):
        se.execute("INSERT INTO t VALUES (9,10,'q')")


def test_drop_index(se):
    se.execute("CREATE INDEX ka ON t (a)")
    se.execute("DROP INDEX ka ON t")
    assert not any(ix.name == "ka"
                   for ix in se.catalog.table("test", "t").indices)
    with pytest.raises(SQLError, match="exists"):
        se.execute("DROP INDEX ka ON t")


# ---------------- ADD / DROP / MODIFY COLUMN ----------------

def test_add_column_with_default(se):
    se.execute("ALTER TABLE t ADD COLUMN c INT DEFAULT 7")
    assert se.query("SELECT c FROM t ORDER BY id") == [(7,), (7,), (7,)]
    se.execute("INSERT INTO t (id, a, b) VALUES (4, 40, 'w')")
    assert se.query("SELECT c FROM t WHERE id = 4") == [(7,)]
    se.execute("INSERT INTO t VALUES (5, 50, 'v', 99)")
    assert se.query("SELECT c FROM t WHERE id = 5") == [(99,)]


def test_add_column_nullable(se):
    se.execute("ALTER TABLE t ADD COLUMN n VARCHAR(5)")
    assert se.query("SELECT n FROM t WHERE id = 1") == [(None,)]
    se.execute("UPDATE t SET n = 'hi' WHERE id = 1")
    assert se.query("SELECT n FROM t WHERE id = 1") == [("hi",)]


def test_add_column_string_default(se):
    se.execute("ALTER TABLE t ADD COLUMN s VARCHAR(5) DEFAULT 'dd'")
    assert se.query("SELECT s FROM t WHERE id = 2") == [("dd",)]
    assert se.query("SELECT COUNT(*) FROM t WHERE s = 'dd'") == [(3,)]


def test_drop_column(se):
    se.execute("ALTER TABLE t DROP COLUMN a")
    assert se.query("SELECT * FROM t WHERE id = 1") == [(1, "x")]
    with pytest.raises(SQLError):
        se.query("SELECT a FROM t")
    # DML still works with the new layout
    se.execute("INSERT INTO t VALUES (4, 'w')")
    assert se.query("SELECT b FROM t WHERE id = 4") == [("w",)]


def test_drop_column_drops_covering_index(se):
    se.execute("CREATE INDEX ka ON t (a)")
    se.execute("ALTER TABLE t DROP COLUMN a")
    assert not any(ix.name == "ka"
                   for ix in se.catalog.table("test", "t").indices)
    # surviving rows + indexes consistent
    assert se.query("SELECT id FROM t WHERE b = 'y'") == [(2,)]


def test_drop_column_guards(se):
    with pytest.raises(SQLError, match="primary key"):
        se.execute("ALTER TABLE t DROP COLUMN id")


def test_modify_column_widen(se):
    se.execute("ALTER TABLE t MODIFY COLUMN a BIGINT")
    assert se.catalog.table("test", "t").column_by_name("a").ftype.kind.name \
        == "BIGINT"
    assert se.query("SELECT a FROM t WHERE id = 3") == [(30,)]


def test_modify_column_to_decimal(se):
    se.execute("ALTER TABLE t MODIFY COLUMN a DECIMAL(10,2)")
    rows = se.query("SELECT a FROM t ORDER BY id")
    assert [str(r[0]) for r in rows] == ["10.00", "20.00", "30.00"]
    # arithmetic in the new domain
    assert str(se.query("SELECT SUM(a) FROM t")[0][0]) == "60.00"


def test_modify_column_narrow_out_of_range(se):
    se.execute("UPDATE t SET a = 300 WHERE id = 1")
    with pytest.raises(SQLError, match="truncated"):
        se.execute("ALTER TABLE t MODIFY COLUMN a TINYINT")
    # rolled back: type unchanged, data intact
    assert se.query("SELECT a FROM t WHERE id = 1") == [(300,)]


# ---------------- RENAME ----------------

def test_rename_table(se):
    se.execute("RENAME TABLE t TO t2")
    assert se.query("SELECT COUNT(*) FROM t2") == [(3,)]
    with pytest.raises(SQLError):
        se.query("SELECT * FROM t")
    se.execute("ALTER TABLE t2 RENAME TO t3")
    assert se.query("SELECT COUNT(*) FROM t3") == [(3,)]


# ---------------- job machinery ----------------

def test_ddl_job_states_recorded(se):
    se.execute("CREATE INDEX ka ON t (a)")
    jobs = se.query("ADMIN SHOW DDL JOBS")
    row = next(j for j in jobs if j[3] == "add_index")
    assert row[4] == "public" and row[5] == "done"


def test_reorg_checkpoint_resume():
    """Worker 'crash' mid-validation: a new worker resumes from the
    checkpoint, not from scratch (reference: ddl/reorg.go:627)."""
    s = Session()
    s.execute("CREATE TABLE big (id INT PRIMARY KEY, v INT)")
    import numpy as np
    info = s.catalog.table("test", "big")
    store = s.storage.table_store(info.id)
    n = 100_000
    store.bulk_load([np.arange(n, dtype=np.int64),
                     np.arange(n, dtype=np.int64)])

    ddl = DDL(s.storage, s.catalog)
    job = ddl.submit("add_index", "test", info,
                     {"name": "uv", "columns": ["v"], "unique": True})
    # walk to write-reorg, then run two validation batches and "crash"
    for _ in range(5):
        done = ddl.step(job)
        assert not done
    assert job.schema_state == "write reorg"
    assert job.reorg_pos > 0
    checkpoint = job.reorg_pos

    # new worker (owner failover) resumes the same queued job
    ddl2 = DDL(s.storage, s.catalog)
    assert s.storage.ddl_jobs == [job]
    ddl2.resume_pending()
    assert job.state == "done"
    assert job.reorg_pos >= checkpoint
    ix = next(ix for ix in s.catalog.table("test", "big").indices
              if ix.name == "uv")
    assert ix.visible and ix.unique
    with pytest.raises(SQLError, match="Duplicate entry"):
        s.execute("INSERT INTO big VALUES (200000, 5)")


def test_reorg_detects_duplicates_across_batches():
    s = Session()
    s.execute("CREATE TABLE big (id INT PRIMARY KEY, v INT)")
    import numpy as np
    info = s.catalog.table("test", "big")
    store = s.storage.table_store(info.id)
    n = 50_000
    vals = np.arange(n, dtype=np.int64)
    vals[-1] = 0  # duplicate of first value, far away in the permutation
    store.bulk_load([np.arange(n, dtype=np.int64), vals])
    ddl = DDL(s.storage, s.catalog)
    job = ddl.submit("add_index", "test", info,
                     {"name": "uv", "columns": ["v"], "unique": True})
    with pytest.raises(DDLError, match="Duplicate entry '0'"):
        ddl.run_job(job)


def test_dml_during_write_reorg():
    """Writes during the reorg phase are unique-checked by the invisible
    index (write-only semantics of the F1 protocol)."""
    s = Session()
    s.execute("CREATE TABLE wr (id INT PRIMARY KEY, v INT)")
    s.execute("INSERT INTO wr VALUES (1, 100), (2, 200)")
    info = s.catalog.table("test", "wr")
    ddl = DDL(s.storage, s.catalog)
    job = ddl.submit("add_index", "test", info,
                     {"name": "uv", "columns": ["v"], "unique": True})
    ddl.step(job)  # none -> delete only (index registered, invisible)
    ddl.step(job)  # -> write only
    # concurrent insert violating the in-progress unique index
    with pytest.raises(SQLError, match="Duplicate entry"):
        s.execute("INSERT INTO wr VALUES (3, 100)")
    s.execute("INSERT INTO wr VALUES (3, 300)")  # non-violating is fine
    # planner must NOT use the invisible index yet
    p = "\n".join(r[0] for r in s.query(
        "EXPLAIN SELECT id FROM wr WHERE v = 100"))
    assert "index:" not in p and "PointGet" not in p
    ddl.run_job(job)
    p = "\n".join(r[0] for r in s.query(
        "EXPLAIN SELECT id FROM wr WHERE v = 100"))
    assert "PointGet" in p


def test_txn_fenced_by_concurrent_ddl():
    """A txn that buffered rows under the old layout must abort when DDL
    rewrites the table before it commits (code-review regression;
    reference: domain/schema_validator.go fencing)."""
    s1 = Session()
    s1.execute("CREATE TABLE f (id INT PRIMARY KEY, a INT, b VARCHAR(5))")
    s1.execute("INSERT INTO f VALUES (1, 10, 'x')")
    s1.execute("BEGIN")
    s1.execute("INSERT INTO f VALUES (2, 20, 'y')")
    s2 = Session(s1.storage)
    s2.execute("ALTER TABLE f DROP COLUMN a")
    with pytest.raises(SQLError, match="schema is changed"):
        s1.execute("COMMIT")
    # table healthy under the new layout
    assert s2.query("SELECT * FROM f") == [(1, "x")]
    s2.execute("INSERT INTO f VALUES (3, 'z')")
    assert s2.query("SELECT COUNT(*) FROM f") == [(2,)]


def test_unique_validation_deleted_row_at_batch_boundary():
    """Duplicates straddling a reorg batch with a deleted row at the
    boundary must still be caught (code-review regression)."""
    import numpy as np
    s = Session()
    s.execute("CREATE TABLE bb (id INT PRIMARY KEY, v INT)")
    info = s.catalog.table("test", "bb")
    store = s.storage.table_store(info.id)
    n = 40_005
    vals = np.arange(n, dtype=np.int64)
    # three rows share v=19998 at adjacent sorted positions; the middle
    # one gets deleted so it sits invisible exactly at the batch boundary
    vals[19999] = 19998
    vals[20000] = 19998
    store.bulk_load([np.arange(n, dtype=np.int64), vals])
    s.execute("DELETE FROM bb WHERE id = 19999")
    s.storage.flush()
    ddl = DDL(s.storage, s.catalog)
    job = ddl.submit("add_index", "test", info,
                     {"name": "uv", "columns": ["v"], "unique": True})
    with pytest.raises(DDLError, match="Duplicate entry '19998'"):
        ddl.run_job(job)


def test_modify_column_large_int_exact():
    """int-family casts must not round-trip through float64
    (code-review regression): values above 2^53 stay exact."""
    s = Session()
    s.execute("CREATE TABLE li (id INT PRIMARY KEY, v BIGINT)")
    big = 4611686018427387905  # 2^62 + 1, not float64-representable
    s.execute(f"INSERT INTO li VALUES (1, {big})")
    s.execute("ALTER TABLE li MODIFY COLUMN v BIGINT NOT NULL")
    assert s.query("SELECT v FROM li") == [(big,)]


def test_multi_spec_alter(se):
    se.execute("ALTER TABLE t ADD COLUMN c INT DEFAULT 1, ADD KEY kc (c)")
    info = se.catalog.table("test", "t")
    assert info.column_by_name("c") is not None
    assert any(ix.name == "kc" for ix in info.indices)


# ---------------- lossy MODIFY COLUMN vs unique keys ----------------

def test_modify_column_lossy_on_unique_rejected():
    """A narrowing cast can collapse distinct values (0.9 and 1.1 -> 1);
    on a uniquely-indexed column that would admit silent duplicates, so
    the change is rejected (reference re-validates during modify reorg,
    ddl/column.go)."""
    s = Session()
    s.execute("CREATE TABLE lm (id INT PRIMARY KEY, d DECIMAL(5,2))")
    s.execute("CREATE UNIQUE INDEX ud ON lm (d)")
    s.execute("INSERT INTO lm VALUES (1, 0.90), (2, 1.10)")
    with pytest.raises(SQLError, match="lossy"):
        s.execute("ALTER TABLE lm MODIFY COLUMN d INT")
    # non-indexed columns may still narrow (values collapse legally)
    s.execute("CREATE TABLE lm2 (id INT PRIMARY KEY, d DECIMAL(5,2))")
    s.execute("INSERT INTO lm2 VALUES (1, 0.90), (2, 1.10)")
    s.execute("ALTER TABLE lm2 MODIFY COLUMN d INT")
    assert s.query("SELECT d FROM lm2 ORDER BY id") == [(1,), (1,)]


def test_modify_column_lossless_on_unique_allowed():
    s = Session()
    s.execute("CREATE TABLE lw (id INT PRIMARY KEY, a INT)")
    s.execute("CREATE UNIQUE INDEX ua ON lw (a)")
    s.execute("INSERT INTO lw VALUES (1, 7), (2, 9)")
    s.execute("ALTER TABLE lw MODIFY COLUMN a BIGINT")
    assert s.query("SELECT a FROM lw WHERE a = 9") == [(9,)]
    s.execute("CREATE TABLE lw2 (id INT PRIMARY KEY, a INT)")
    s.execute("CREATE UNIQUE INDEX ua2 ON lw2 (a)")
    s.execute("INSERT INTO lw2 VALUES (1, 7)")
    # INT needs 10 integer digits: DECIMAL(12,2) holds them losslessly
    s.execute("ALTER TABLE lw2 MODIFY COLUMN a DECIMAL(12,2)")
    assert s.query("SELECT id FROM lw2 WHERE a = 7") == [(1,)]


def test_modify_column_swaps_type_and_data_atomically():
    """The rewritten epoch and the new TableInfo publish in one step: a
    DECIMAL(10,2)->DECIMAL(10,4) rescale must never be readable at the
    old scale."""
    s = Session()
    s.execute("CREATE TABLE at2 (id INT PRIMARY KEY, d DECIMAL(10,2))")
    s.execute("INSERT INTO at2 VALUES (1, 12.34)")
    s.execute("ALTER TABLE at2 MODIFY COLUMN d DECIMAL(10,4)")
    assert str(s.query("SELECT d FROM at2")[0][0]) == "12.3400"
