"""SQL diagnostics plane: the obs_inspect rules engine + metrics_schema.

Pins the ISSUE-10 acceptance criteria: every shipped rule fires under
its armed failpoint or synthetic telemetry and stays silent on a
healthy server; `SELECT * FROM information_schema.inspection_result`
on a server with an armed mesh-skew (or fsync-stall) failpoint returns
the rule row with severity + reference text; the same query via
cluster_inspection_result returns rows from both members of a
two-process cluster with per-peer degradation; diagnostics.enabled =
false does ZERO inspection work on the statement path; critical
findings are edge-triggered into the event ring; and the whole plane
is thread-free.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
import urllib.request

import pytest

from tidb_tpu import obs, obs_inspect
from tidb_tpu.copr import mesh as M
from tidb_tpu.copr.client import CopClient
from tidb_tpu.rpc.client import RpcOptions
from tidb_tpu.session import Session
from tidb_tpu.store.storage import Storage
from tidb_tpu.util import failpoint

OPTS = RpcOptions(connect_timeout_ms=1000, request_timeout_ms=4000,
                  backoff_budget_ms=3000, lock_budget_ms=8000,
                  lease_ms=2000)

RESULT_SQL = ("select rule, item, severity, value, reference, details "
              "from information_schema.inspection_result")


@pytest.fixture(autouse=True)
def _clean_failpoints():
    yield
    failpoint.disable_all()


def _rows_for_rule(session, rule: str):
    return [r for r in session.execute(RESULT_SQL).rows if r[0] == rule]


# ==================== registry / config mirror ====================

def test_state_mirrors_config_section():
    """config.DiagnosticsConfig and obs_inspect.DiagnosticsState are
    mirrored definitions (config never imports the obs chain); every
    config knob must exist on the runtime state with the same default,
    so seed_diagnostics cannot silently drop a knob."""
    from tidb_tpu.config import DiagnosticsConfig

    state_fields = {f.name: f for f in
                    dataclasses.fields(obs_inspect.DiagnosticsState)}
    for f in dataclasses.fields(DiagnosticsConfig):
        assert f.name in state_fields, f"state lacks {f.name}"
        assert f.default == state_fields[f.name].default, f.name


def test_seed_diagnostics_applies_and_keeps_edge_memory():
    from tidb_tpu.config import Config

    st = Storage()
    st.diagnostics.seen_critical = {("a", "b")}
    cfg = Config()
    cfg.diagnostics.enabled = False
    cfg.diagnostics.fsync_stall_threshold = 9
    cfg.seed_diagnostics(st)
    assert st.diagnostics.enabled is False
    assert st.diagnostics.fsync_stall_threshold == 9
    assert st.diagnostics.seen_critical == {("a", "b")}


# ==================== healthy server: silence ====================

def test_healthy_server_has_no_findings():
    st = Storage()
    s = Session(st)
    s.execute("create table h (a int primary key)")
    s.execute("insert into h values (1),(2)")
    s.execute("select count(*) from h")
    assert s.execute(RESULT_SQL).rows == []
    # summary still lists every registered rule (the queryable registry)
    rows = s.execute("select rule, findings from "
                     "information_schema.inspection_summary").rows
    assert {r[0] for r in rows} == set(obs_inspect.RULES)
    assert all(r[1] == 0 for r in rows), rows


# ==================== per-rule firing (synthetic telemetry) =========

def test_fsync_stall_rule_fires_with_reference():
    """The acceptance path: an fsync-stall burst surfaces as an
    inspection_result row with severity and the rule's reference."""
    st = Storage()
    s = Session(st)
    for i in range(st.diagnostics.fsync_stall_threshold):
        st.obs.events.record("fsync_stall", severity="warn",
                             detail=f"wal fsync took 150.0ms #{i}")
    rows = _rows_for_rule(s, "wal-fsync-stall")
    assert rows, s.execute(RESULT_SQL).rows
    rule, item, sev, value, ref, details = rows[0]
    assert item == "wal" and sev == "warning"
    assert int(value) >= st.diagnostics.fsync_stall_threshold
    assert "sync-log" in ref
    assert "150.0ms" in details
    # one stall under the threshold stays silent
    st2 = Storage()
    st2.obs.events.record("fsync_stall", severity="warn", detail="x")
    assert _rows_for_rule(Session(st2), "wal-fsync-stall") == []


def test_governor_kill_and_admission_shed_rules():
    st = Storage()
    s = Session(st)
    st.obs.events.record("governor_kill", severity="warn", conn_id=7,
                         detail="usage 100 > server-memory-limit 50")
    st.obs.events.record("admission_shed", severity="warn", conn_id=8,
                         detail="queue wait exceeded")
    kills = _rows_for_rule(s, "governor-kill")
    sheds = _rows_for_rule(s, "admission-shed")
    assert kills and kills[0][2] == "warning"
    assert sheds and sheds[0][2] == "warning"
    # 3x the kill threshold escalates to critical
    for _ in range(3):
        st.obs.events.record("governor_kill", severity="warn",
                             detail="more")
    assert _rows_for_rule(s, "governor-kill")[0][2] == "critical"


def test_host_fallback_rule_reads_topsql():
    st = Storage()
    s = Session(st)
    st.obs.topsql.configure(enabled=True, window_s=3600)
    st.obs.topsql.record(
        "cafe" * 8, "select slow ( ? )", "test", 1.0,
        stages={"host_fallback": 0.9, "plan_build": 0.1}, rows=10)
    st.obs.topsql.record(
        "beef" * 8, "select fast ( ? )", "test", 1.0,
        stages={"kernel": 0.9, "plan_build": 0.1}, rows=10)
    rows = _rows_for_rule(s, "top-sql-host-fallback")
    assert len(rows) == 1 and rows[0][1] == "cafe" * 8, rows
    assert "host_fallback" in rows[0][5]
    # disabled plane: rule is silent (no attribution to read)
    st.obs.topsql.configure(enabled=False)
    assert _rows_for_rule(s, "top-sql-host-fallback") == []


def test_registry_row_eval_rule_fires_after_fallback():
    """The de-vectorization satellite: a registry-fallback scalar
    function bumps tidb_registry_row_eval_total{func} and the rule
    reports the per-row rows inside the history window. INSERT() is
    still rowwise; SUBSTRING_INDEX / REGEXP_LIKE over dictionary
    columns now evaluate per DISTINCT value (npeval._dict_vec_call)
    and must NOT count as row evals."""
    st = Storage()
    s = Session(st)
    s.execute("create table rr (a int primary key, b varchar(16))")
    s.execute("insert into rr values (1,'a.b.c'),(2,'d.e.f')")
    st.metrics_history.sample_now()  # window baseline
    base_si = obs.REGISTRY_ROW_EVALS.get(func="SUBSTRING_INDEX")
    base_rl = obs.REGISTRY_ROW_EVALS.get(func="REGEXP_LIKE")
    s.execute("select substring_index(b, '.', 1) from rr")
    s.execute("select a from rr where regexp_like(b, '^a')")
    assert obs.REGISTRY_ROW_EVALS.get(
        func="SUBSTRING_INDEX") == base_si, \
        "SUBSTRING_INDEX over a dict column must dict-vectorize"
    assert obs.REGISTRY_ROW_EVALS.get(func="REGEXP_LIKE") == base_rl, \
        "REGEXP_LIKE over a dict column must dict-vectorize"
    base = obs.REGISTRY_ROW_EVALS.get(func="INSERT")
    s.execute("select insert(b, 1, 1, 'Z') from rr")
    assert obs.REGISTRY_ROW_EVALS.get(func="INSERT") > base
    rows = _rows_for_rule(s, "registry-row-eval")
    assert rows and 'func="INSERT"' in rows[0][1], rows
    assert int(rows[0][3]) >= 2


def test_breaker_and_heartbeat_rules_from_transport_state():
    st = Storage()
    s = Session(st)
    st.transport_health = lambda: {
        "mode": "socket-follower", "peer": "10.0.0.1:4001",
        "breaker": "open", "breaker_fail_streak": 3,
        "last_contact_age_s": 9.5,
        "members": [
            {"addr": "10.0.0.1:4001", "role": "leader",
             "hb_age_s": 0.1},
            {"addr": "10.0.0.2:0", "role": "follower",
             "hb_age_s": 99.0},
            {"addr": "10.0.0.3:0", "role": "follower",
             "down": "RPCError: dead"},
        ]}
    brk = _rows_for_rule(s, "rpc-breaker-open")
    assert brk and brk[0][2] == "critical" and brk[0][3] == "open"
    hb = _rows_for_rule(s, "follower-heartbeat-stale")
    items = {r[1]: r[2] for r in hb}
    assert items.get("10.0.0.2:0") == "critical"  # 99s >= 3x10s
    assert items.get("10.0.0.3:0") == "critical"  # down
    assert "10.0.0.1:4001" not in items


def test_metric_cardinality_rule_promotes_lint():
    st = Storage()
    s = Session(st)
    g = st.obs.metrics.gauge("tidb_test_wide_bytes", "per-device")
    for i in range(64):  # way past any mesh width
        g.set(1.0, device=f"dev{i}")
    rows = _rows_for_rule(s, "metric-cardinality")
    assert any("tidb_test_wide_bytes" in r[1] for r in rows), rows


# ==================== mesh rules (real dispatches) ====================

@pytest.fixture()
def mesh_cluster():
    single = Session(cop=CopClient())
    single.execute("create table dim (k int not null primary key, "
                   "tag varchar(8) not null)")
    single.execute("create table fact (id int not null primary key, "
                   "k int not null, v int not null)")
    single.execute("insert into dim values (1,'a'),(2,'b'),(3,'c')")
    vals = ",".join(f"({i},{i % 3 + 1},{i % 100})"
                    for i in range(1, 6001))
    single.execute(f"insert into fact values {vals}")
    single.storage.flush()
    plane = M.MeshPlane(M.MeshConfig(enabled=True,
                                     shard_threshold_rows=512))
    mesh = Session(single.storage,
                   cop=plane.client_for(single.storage))
    return single, mesh, plane


JOIN_SQL = ("select dim.tag, sum(fact.v) from fact join dim "
            "on fact.k = dim.k group by dim.tag order by dim.tag")


def test_mesh_skew_failpoint_fires_inspection(mesh_cluster):
    """THE acceptance criterion: armed mesh-skew failpoint ->
    SELECT * FROM information_schema.inspection_result returns the
    mesh-shard-skew row with severity and reference text, the critical
    crossing edge-triggers ONE inspection_finding event, and SHOW
    WARNINGS carries the critical finding after the SELECT."""
    single, mesh, plane = mesh_cluster
    st = single.storage
    st.diagnostics.skew_min_dispatches = 1
    with failpoint.failpoint("mesh/skew", 64.0):
        mesh.query(JOIN_SQL)
    rows = _rows_for_rule(mesh, "mesh-shard-skew")
    assert rows, mesh.execute(RESULT_SQL).rows
    rule, item, sev, value, ref, details = rows[0]
    assert sev == "critical"  # 64 >= 2 * skew-warn-ratio(4.0)
    assert float(value) >= plane.cfg.skew_warn_ratio
    assert "skew-warn-ratio" in ref
    # SHOW WARNINGS linkage: the SELECT left the critical finding there
    warns = mesh.execute("show warnings").rows
    assert any("mesh-shard-skew" in str(w[2]) for w in warns), warns
    # edge-triggered event: first crossing recorded, re-reads are quiet
    evs = [e for e in st.obs.events.snapshot()
           if e["kind"] == "inspection_finding"]
    assert evs and "mesh-shard-skew" in evs[-1]["detail"]
    n = len(evs)
    mesh.execute(RESULT_SQL)
    evs = [e for e in st.obs.events.snapshot()
           if e["kind"] == "inspection_finding"]
    assert len(evs) == n, "critical finding re-fired (level-triggered)"


def test_mesh_skew_rule_ignores_transient_single_hit():
    """'Sustained' means skew_min_dispatches dispatches INDIVIDUALLY
    crossed the warn ratio — one transient hot range among 100
    balanced dispatches must not read as a critical finding forever
    (the recorder's monotonic max_skew alone would)."""
    from types import SimpleNamespace as NS

    cfg = obs_inspect.DiagnosticsState()
    cfg.skew_min_dispatches = 2
    plane_cfg = NS(skew_warn_ratio=4.0)
    client = NS(recorder=NS(plane=NS(cfg=plane_cfg)))
    now = time.time()
    ent = {"digest": "d" * 32, "kind": "frag", "op": "join",
           "dispatches": 100, "shards": 8, "last_rows": [1] * 8,
           "last_skew": 1.0, "max_skew": 9.0,
           "skew_hits": [(now, 9.0)],
           "in_rows": 800, "out_rows": 100, "routed_bytes": 0}
    ctx = NS(cfg=cfg, mesh_client=client, now=now, window_s=120.0,
             mesh={"dispatches": [ent], "compiles": []})
    assert obs_inspect._r_mesh_skew(ctx) == []  # transient: silent
    ent["skew_hits"] = [(now - 1.0, 9.0), (now, 4.2)]
    out = obs_inspect._r_mesh_skew(ctx)
    assert out and out[0].severity == "critical"  # 9.0 >= 2 * 4.0
    assert "2 of 100 dispatches" in out[0].details
    # both crossings left the window long ago — a long-fixed digest
    # must not stay flagged until ring eviction
    ent["skew_hits"] = [(now - 3600.0, 9.0), (now - 3500.0, 9.0)]
    assert obs_inspect._r_mesh_skew(ctx) == []
    # an OLD spike must not escalate CURRENT mild skew: two in-window
    # crossings at 4.2 grade warning even though lifetime max was 9.0
    ent["skew_hits"] = [(now - 3600.0, 9.0), (now - 1.0, 4.2),
                        (now, 4.2)]
    out = obs_inspect._r_mesh_skew(ctx)
    assert out and out[0].severity == "warning" and \
        out[0].value == "4.20", out


def test_mesh_recompile_storm_rule(mesh_cluster):
    single, mesh, plane = mesh_cluster
    client = M.client_of(single.storage)
    for _ in range(client.recorder.STORM_COMPILES):
        client.recorder.note_compile("frag", "sig-hot", 0.2,
                                     full_key="k1")
    rows = _rows_for_rule(mesh, "mesh-recompile-storm")
    assert rows and rows[0][1] == "sig-hot"
    assert int(rows[0][3]) >= client.recorder.STORM_COMPILES


def test_mesh_hbm_watermark_rule_from_event(mesh_cluster):
    single, mesh, _ = mesh_cluster
    single.storage.obs.events.record(
        "mesh_hbm_watermark", severity="warn",
        detail="device TFRT_CPU_0: 900 live buffer bytes >= 85% of "
               "1000-byte capacity")
    rows = _rows_for_rule(mesh, "mesh-hbm-watermark")
    assert rows and rows[0][2] == "critical"
    assert rows[0][1] == "device TFRT_CPU_0"


# ==================== cluster fan-out ====================

@pytest.fixture()
def cluster(tmp_path):
    leader = Storage(str(tmp_path / "leader"), shared=True,
                     rpc_listen="127.0.0.1:0", rpc_options=OPTS)
    follower = Storage(str(tmp_path / "follower"),
                       remote=f"127.0.0.1:{leader.rpc_server.port}",
                       rpc_options=OPTS)
    try:
        yield leader, follower
    finally:
        follower.close()
        leader.close()


def test_cluster_inspection_rows_from_both_members(cluster):
    leader, follower = cluster
    for st in (leader, follower):
        for i in range(st.diagnostics.fsync_stall_threshold):
            st.obs.events.record("fsync_stall", severity="warn",
                                 detail=f"wal fsync took 200ms #{i}")
    for s in (Session(leader), Session(follower)):
        rows = s.execute(
            "select instance, rule, severity, error from "
            "information_schema.cluster_inspection_result").rows
        by_inst = {r[0] for r in rows
                   if r[1] == "wal-fsync-stall" and r[3] is None}
        assert by_inst == {leader.diag_address, follower.diag_address}
    # the embedded leader runs sync-log=off with a live follower: the
    # config-mismatch rule fires on the leader only, with NO synthetic
    # telemetry at all
    sl = Session(leader)
    rows = sl.execute(
        "select instance, rule from "
        "information_schema.cluster_inspection_result "
        "where rule = 'config-sync-log'").rows
    assert {r[0] for r in rows} == {leader.diag_address}, rows


def test_cluster_inspection_peer_down_degrades(cluster):
    leader, follower = cluster
    sl = Session(leader)
    failpoint.enable("diag/peer-down")
    try:
        rows = sl.execute(
            "select instance, rule, error from "
            "information_schema.cluster_inspection_result").rows
    finally:
        failpoint.disable("diag/peer-down")
    err = [r for r in rows if r[2] is not None]
    assert err and any(follower.diag_address == r[0] for r in err)
    assert any("peer-down" in r[2] for r in err)
    assert any("unreachable" in w[2] for w in sl.warnings), sl.warnings


# ==================== zero work while disabled ====================

def test_disabled_does_zero_inspection_work():
    st = Storage()
    s = Session(st)
    st.obs.events.record("fsync_stall", severity="warn", detail="x")
    st.diagnostics.enabled = False
    orig = obs_inspect.InspectionContext.__init__

    def _boom(*a, **k):
        raise AssertionError("inspection built a context while disabled")

    obs_inspect.InspectionContext.__init__ = _boom
    try:
        assert s.execute(RESULT_SQL).rows == []
        assert s.execute("select * from "
                         "information_schema.inspection_summary"
                         ).rows == []
        assert st.diag.diag_inspection() == {"rows": []}
        assert obs_inspect.status_section(st) == {
            "enabled": False, "rules": len(obs_inspect.RULES)}
        assert obs_inspect.debug_payload(st)["enabled"] is False
    finally:
        obs_inspect.InspectionContext.__init__ = orig
    # no inspection_finding events either
    assert not [e for e in st.obs.events.snapshot()
                if e["kind"] == "inspection_finding"]


def test_inspection_runs_no_threads():
    st = Storage()
    before = {t.ident for t in threading.enumerate()}
    for i in range(3):
        st.obs.events.record("fsync_stall", severity="warn", detail="x")
    obs_inspect.inspect(st)
    after = {t.ident for t in threading.enumerate()}
    assert after <= before, "inspection spawned threads"


def test_broken_rule_degrades_to_info_finding():
    st = Storage()

    def _explode(ctx):
        raise RuntimeError("rule bug")

    r = obs_inspect.Rule("test-broken", "warning", "ref", _explode)
    obs_inspect.RULES["test-broken"] = r
    try:
        findings = [f for f in obs_inspect.inspect(st)
                    if f.rule == "test-broken"]
        assert findings and findings[0].severity == "info"
        assert "RuntimeError" in findings[0].details
    finally:
        del obs_inspect.RULES["test-broken"]


# ==================== metrics_schema tier ====================

def test_metrics_schema_point_and_time_range_rows():
    st = Storage()
    s = Session(st)
    s.execute("create table mt (a int primary key)")
    s.execute("insert into mt values (1)")
    s.execute("select * from mt")
    # two ring samples + the live point
    st.metrics_history.sample_now()
    time.sleep(0.02)
    st.metrics_history.sample_now()
    rows = s.execute(
        "select time, ts, labels, value from "
        "metrics_schema.tidb_queries_total "
        "where labels = 'type=\"Select\"'").rows
    assert len(rows) >= 3, rows  # 2 history points + now
    ts = [r[1] for r in rows]
    assert ts == sorted(ts)
    assert all(r[3] >= 1 for r in rows)
    # point-in-time: the LAST row is the live sample and aggregates work
    total = s.execute(
        "select max(value) from metrics_schema.tidb_queries_total "
        "where labels = 'type=\"Select\"'").rows[0][0]
    assert total >= rows[-1][3]


def test_metrics_schema_show_tables_and_unknown_table():
    from tidb_tpu.catalog import metrics_schema as MS
    from tidb_tpu.session.session import SQLError

    st = Storage()
    s = Session(st)
    s.execute("use metrics_schema")
    tables = {r[0] for r in s.execute("show tables").rows}
    assert "tidb_queries_total" in tables
    assert "tidb_registry_row_eval_total" in tables
    assert tables == set(MS.families(st)), "tables != live families"
    with pytest.raises(SQLError):
        s.execute("select * from metrics_schema.tidb_no_such_family")


def test_metrics_schema_serves_process_and_server_registries():
    st = Storage()
    s = Session(st)
    # server-registry family and process-registry family both resolve
    for t in ("tidb_commits_total", "tidb_process_rss_bytes"):
        rows = s.execute(f"select value from metrics_schema.{t}").rows
        assert rows is not None
    # the RSS gauge probe ran at read time: live value is nonzero
    rows = s.execute("select max(value) from "
                     "metrics_schema.tidb_process_rss_bytes").rows
    assert rows[0][0] > 0


# ==================== status port surfaces ====================

def test_debug_inspection_route_and_status_section():
    from tidb_tpu.server.server import Server

    storage = Storage()
    srv = Server(storage, host="127.0.0.1", port=0, status_port=0)
    srv.start()
    try:
        for i in range(storage.diagnostics.fsync_stall_threshold):
            storage.obs.events.record("fsync_stall", severity="warn",
                                      detail=f"stall {i}")
        base = f"http://127.0.0.1:{srv.status_port}"
        insp = json.loads(urllib.request.urlopen(
            base + "/debug/inspection", timeout=10).read())
        assert insp["enabled"] is True
        assert set(insp["rules"]) == set(obs_inspect.RULES)
        assert any(f["rule"] == "wal-fsync-stall"
                   for f in insp["findings"]), insp
        status = json.loads(urllib.request.urlopen(
            base + "/status", timeout=10).read())
        sec = status["inspection"]
        assert sec["enabled"] is True
        assert sec["rules"] == len(obs_inspect.RULES)
        assert sec["findings"]["warning"] >= 1, sec
    finally:
        srv.close()
        # Server.start() armed the metrics-history sampler; only
        # Storage.close() joins it — without this the thread outlives
        # the test and trips the diag-thread hygiene assertions
        storage.close()
