"""Column-level privileges: GRANT SELECT (a, b) ON t — enforcement at
the plan's pruned scan columns for reads and at the target column list
for DML (reference: mysql.columns_priv; privilege/privileges/cache.go
columnsPriv; executor/grant.go column scope)."""

import pytest

from testkit import TestKit
from tidb_tpu.session import Session


@pytest.fixture()
def tk():
    t = TestKit()
    t.must_exec("create table ct (a int, b int, secret int)")
    t.must_exec("insert into ct values (1, 10, 99), (2, 20, 98)")
    return t


def _user(tk, name):
    tk.must_exec(f"create user '{name}' identified by ''")
    s = Session(tk.session.storage)
    s.execute("use test")
    s.user = name
    return s


def test_column_select_scope(tk):
    u = _user(tk, "c1")
    tk.must_exec("grant select (a, b) on ct to 'c1'")
    assert u.execute("select a, b from ct order by a").rows == \
        [(1, 10), (2, 20)]
    # the projection is pruned, so an unused column is not touched
    assert u.execute("select a from ct where b > 15").rows == [(2,)]
    with pytest.raises(Exception) as ei:
        u.execute("select secret from ct")
    assert "secret" in str(ei.value)
    with pytest.raises(Exception):
        u.execute("select * from ct")  # star expands to secret
    with pytest.raises(Exception):
        u.execute("select a from ct where secret > 0")  # filter touch


def test_column_insert_update_scope(tk):
    u = _user(tk, "c2")
    tk.must_exec("grant insert (a, b), select (a, b) on ct to 'c2'")
    u.execute("insert into ct (a, b) values (3, 30)")
    with pytest.raises(Exception):
        u.execute("insert into ct (a, secret) values (4, 1)")
    tk.must_exec("grant update (b) on ct to 'c2'")
    u.execute("update ct set b = 31 where a = 3")
    with pytest.raises(Exception):
        u.execute("update ct set secret = 0 where a = 3")


def test_full_table_grant_bypasses_column_checks(tk):
    u = _user(tk, "c3")
    tk.must_exec("grant select on ct to 'c3'")
    assert len(u.execute("select * from ct").rows) == 2


def test_revoke_column_grant(tk):
    u = _user(tk, "c4")
    tk.must_exec("grant select (a, b) on ct to 'c4'")
    assert len(u.execute("select a from ct").rows) == 2
    tk.must_exec("revoke select (b) on ct from 'c4'")
    with pytest.raises(Exception):
        u.execute("select b from ct")
    assert len(u.execute("select a from ct").rows) == 2


def test_show_grants_renders_columns(tk):
    _user(tk, "c5")
    tk.must_exec("grant select (b, a) on ct to 'c5'")
    rows = tk.must_query("show grants for 'c5'")
    assert any("SELECT (a, b) ON test.ct" in r[0] for r in rows), rows


def test_usage_alignment_with_column_lists(tk):
    """GRANT USAGE, SELECT (a) must scope SELECT to column a — not
    table-wide via index misalignment."""
    u = _user(tk, "c7")
    tk.must_exec("grant usage, select (a) on ct to 'c7'")
    assert len(u.execute("select a from ct").rows) == 2
    with pytest.raises(Exception):
        u.execute("select secret from ct")


def test_view_mediated_access_still_works(tk):
    u = _user(tk, "c8")
    tk.must_exec("create view vw as select a, b from ct")
    tk.must_exec("grant select on vw to 'c8'")
    assert len(u.execute("select a from vw").rows) == 2


def test_partial_grant_failure_mutates_nothing(tk):
    pm = tk.session.storage.privileges
    _user(tk, "c9")
    with pytest.raises(Exception):
        # column scope on a db wildcard is invalid: the whole statement
        # must apply nothing
        tk.must_exec("grant select, insert (a) on test.* to 'c9'")
    assert pm.grants_for("c9") == []


def test_update_requires_select_on_read_columns(tk):
    u = _user(tk, "c10")
    tk.must_exec("grant update (a), select (a) on ct to 'c10'")
    u.execute("update ct set a = 5 where a = 1")
    with pytest.raises(Exception):
        u.execute("update ct set a = 6 where secret = 99")
    with pytest.raises(Exception):
        u.execute("update ct set a = secret where a = 5")


def test_processlist_requires_process_priv(tk):
    # embedded sessions list only themselves; the gate matters on the
    # wire path — exercised via the provider directly
    tk.session.storage.processlist = lambda: [
        (1, "root", "h", "test", "Query", 0, "", "select 1"),
        (2, "c11", "h", "test", "Query", 0, "", "select 2")]
    u = _user(tk, "c11")
    rows = u.execute("show processlist").rows
    assert [r[1] for r in rows] == ["c11"]
    tk.must_exec("grant process on *.* to 'c11'")
    assert len(u.execute("show processlist").rows) == 2
    del tk.session.storage.processlist


def test_column_grants_through_roles(tk):
    tk.must_exec("create role 'colrole'")
    tk.must_exec("grant select (a) on ct to 'colrole'")
    u = _user(tk, "c6")
    tk.must_exec("grant 'colrole' to 'c6'")
    u.execute("set role 'colrole'")
    assert len(u.execute("select a from ct").rows) == 2
    with pytest.raises(Exception):
        u.execute("select b from ct")
