"""Tier-1 ratchet for the PR 9 datagen memmap cache (ISSUE 12): the
SF100-blocker fix (streamed, cached, bounded-RSS lineitem generation)
previously had no test. Runs the real bench.generate_lineitem_chunked
at toy scale against a tmp cache dir."""

import os

import numpy as np
import pytest

import bench


@pytest.fixture()
def cache_env(tmp_path, monkeypatch):
    monkeypatch.setenv("BENCH_CACHE_DIR", str(tmp_path / "cache"))
    return tmp_path / "cache"


def _gen(n: int, chunk: int):
    with bench._Heartbeat("datagen-test", interval_s=3600) as hb:
        out = bench.generate_lineitem_chunked(n, hb, chunk=chunk)
        assert hb.rows == n
    return out


def _tag_dirs(cache_dir) -> list:
    return sorted(p.name for p in cache_dir.iterdir()) \
        if cache_dir.exists() else []


def test_cache_write_then_hit(cache_env):
    n, chunk = 4000, 1000
    first = _gen(n, chunk)
    tags = _tag_dirs(cache_env)
    assert len(tags) == 1
    tag = cache_env / tags[0]
    assert (tag / "_COMPLETE").exists()
    # every column materialized at full length, reopened read-only
    # mapped (the bounded-RSS contract: pages are cache-evictable)
    for c in bench._LI_COLS:
        assert (tag / f"{c}.npy").exists()
        assert isinstance(first[c], np.memmap), type(first[c])
        assert not first[c].flags.writeable
        assert len(first[c]) == n
    # second generate: pure cache hit, identical bytes
    second = _gen(n, chunk)
    assert _tag_dirs(cache_env) == tags  # no new tag dir
    for c in bench._LI_COLS:
        assert isinstance(second[c], np.memmap)
        assert np.array_equal(first[c], second[c])


def test_incomplete_cache_not_trusted(cache_env):
    """A crashed writer leaves columns without the _COMPLETE marker:
    the next run regenerates instead of mapping garbage."""
    n, chunk = 4000, 1000
    first = _gen(n, chunk)
    tag = cache_env / _tag_dirs(cache_env)[0]
    os.unlink(tag / "_COMPLETE")
    # poison a column: if the marker were ignored, this would surface
    data = np.lib.format.open_memmap(tag / "l_quantity.npy", mode="r+")
    data[:16] = -777
    data.flush()
    del data
    again = _gen(n, chunk)
    assert (tag / "_COMPLETE").exists()
    assert not (np.asarray(again["l_quantity"][:16]) == -777).any()
    assert np.array_equal(first["l_orderkey"], again["l_orderkey"])


def test_gen_version_invalidates(cache_env, monkeypatch):
    n, chunk = 4000, 1000
    _gen(n, chunk)
    tags_v1 = _tag_dirs(cache_env)
    monkeypatch.setattr(bench, "GEN_VERSION", bench.GEN_VERSION + 1)
    _gen(n, chunk)
    tags_v2 = _tag_dirs(cache_env)
    assert len(tags_v2) == 2 and set(tags_v1) < set(tags_v2)
    assert any(f"v{bench.GEN_VERSION}" in t for t in tags_v2)


def test_chunk_size_is_part_of_identity(cache_env):
    """Chunks are seeded independently, so the concrete rows are a
    function of the chunk size — different chunking must not alias."""
    _gen(4000, 1000)
    _gen(4000, 2000)
    assert len(_tag_dirs(cache_env)) == 2


def test_small_n_bypasses_cache(cache_env):
    out = _gen(500, 1000)  # n <= chunk: plain in-memory generation
    assert not _tag_dirs(cache_env)
    assert len(out["l_orderkey"]) == 500


def test_transient_rss_bounded_by_chunk(cache_env):
    """The whole point of the streamed path: generating n rows must
    not hold n rows of temporaries. At toy scale we assert the tracked
    allocation delta stays near ONE chunk, not the full dataset."""
    import tracemalloc

    n, chunk = 64_000, 8_000
    tracemalloc.start()
    with bench._Heartbeat("datagen-rss", interval_s=3600) as hb:
        out = bench.generate_lineitem_chunked(n, hb, chunk=chunk)
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    per_row = 115  # bytes/row, the bench's own sizing constant
    # generous: a few chunks of temporaries, but nowhere near n rows
    assert peak < 6 * chunk * per_row, \
        f"peak {peak / 1e6:.1f}MB suggests whole-dataset materialization"
    assert len(out["l_orderkey"]) == n
