"""Benchmark board: TPC-H (SF10 + SF100), SSB, ClickBench-style configs.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} — the
headline is TPC-H Q6 at the north-star SF100 scale (BASELINE.json
metric: "TPC-H rows/sec/chip; Q1+Q6 p50 latency at SF100").

Comparison basis (BASELINE.md): the reference publishes no absolute
numbers in-repo and its Go toolchain isn't present here, so the floor is
a row-at-a-time interpreted coprocessor baseline measured in-process —
the execution model of the reference's mocktikv interpreter (reference:
store/mockstore/mocktikv/cop_handler_dag.go:150, row loop over MVCC
pairs) — timed on a sample and scaled. BOTH sides of the headline ratio
are SINGLE-STREAM.

Configs (BASELINE.json configs[0..4] + the r04 join target):
  q6_sf10 / q1_sf10     — scan flight at SF10 (series continuity)
  q3_sf10 / q5_sf10     — snowflake join fragments at SF10 (digest vs
                          exact numpy oracle; plan verified vs sqlite at
                          SF0.1 by the test suite)
  ssb q1.1-1.3          — SSB flight at BENCH_SSB_SF (default 100)
  cb_*                  — ClickBench-style wide scan/TopN at
                          BENCH_CB_ROWS (default 100M)
  q6_sf100 / q1_sf100   — the north star (BENCH_SF_BIG, default 100)

Every timed query passes an exact digest check against a numpy oracle
first. Environment knobs: BENCH_SF (10), BENCH_JOIN_SF (10),
BENCH_SSB_SF (100), BENCH_CB_ROWS (1e8), BENCH_SF_BIG (100),
BENCH_REPEAT (5), BENCH_CLIENTS (8), BENCH_PLATFORM.
"""

from __future__ import annotations

import gc
import json
import os
import sys
import time

import numpy as np

ROWS_PER_SF = 6_001_215


def _rss_gb() -> float:
    try:
        with open("/proc/self/status") as f:
            for ln in f:
                if ln.startswith("VmRSS"):
                    return int(ln.split()[1]) / 1e6
    except OSError:
        pass
    return 0.0


def log(msg: str) -> None:
    print(f"# [rss={_rss_gb():.1f}G] {msg}", file=sys.stderr, flush=True)


def interpreted_q6_baseline(arrays, sample: int = 200_000) -> float:
    """Row-at-a-time interpreted Q6 (mocktikv-style) rows/sec, median of
    3 (single passes are noisy and the ratio inherits it)."""
    from tidb_tpu.types.value import parse_date

    n = min(sample, len(arrays["l_shipdate"]))
    ship = arrays["l_shipdate"][:n].tolist()
    disc = arrays["l_discount"][:n].tolist()
    qty = arrays["l_quantity"][:n].tolist()
    price = arrays["l_extendedprice"][:n].tolist()
    d1, d2 = parse_date("1994-01-01"), parse_date("1995-01-01")
    rates = []
    for _ in range(3):
        t0 = time.perf_counter()
        acc = 0
        for i in range(n):
            s = ship[i]
            if s >= d1 and s < d2:
                d = disc[i]
                if 5 <= d <= 7 and qty[i] < 2400:
                    acc += price[i] * d
        rates.append(n / (time.perf_counter() - t0))
    return sorted(rates)[1]


def q6_oracle(arrays) -> int:
    from tidb_tpu.types.value import parse_date

    d1, d2 = parse_date("1994-01-01"), parse_date("1995-01-01")
    m = ((arrays["l_shipdate"] >= d1) & (arrays["l_shipdate"] < d2)
         & (arrays["l_discount"] >= 5) & (arrays["l_discount"] <= 7)
         & (arrays["l_quantity"] < 2400))
    return int((arrays["l_extendedprice"][m].astype(np.int64)
                * arrays["l_discount"][m]).sum())


def q1_oracle(arrays):
    """Exact int64 aggregates per (returnflag, linestatus) group."""
    from tidb_tpu.types.value import parse_date

    cutoff = parse_date("1998-12-01") - 90
    m = arrays["l_shipdate"] <= cutoff
    rf = arrays["l_returnflag"][m]
    ls = arrays["l_linestatus"][m]
    qty = arrays["l_quantity"][m].astype(np.int64)
    ext = arrays["l_extendedprice"][m].astype(np.int64)
    disc = arrays["l_discount"][m].astype(np.int64)
    tax = arrays["l_tax"][m].astype(np.int64)
    key = rf * 2 + ls
    out = {}
    for name, vals in (("qty", qty), ("base", ext),
                       ("disc_price", ext * (100 - disc)),
                       ("charge", ext * (100 - disc) * (100 + tax)),
                       ("count", np.ones(len(key), np.int64))):
        acc = np.zeros(6, dtype=np.int64)
        np.add.at(acc, key, vals)
        out[name] = acc
    res = {}
    for k in range(6):
        if out["count"][k]:
            res[(k // 2, k % 2)] = tuple(int(out[n][k]) for n in (
                "qty", "base", "disc_price", "charge", "count"))
    return res


def check_q1(rows, arrays) -> None:
    want = q1_oracle(arrays)
    flag_code = {"A": 0, "R": 1, "N": 2}
    status_code = {"F": 0, "O": 1}
    assert len(rows) == len(want), (len(rows), len(want))
    for r in rows:
        key = (flag_code[r[0]], status_code[r[1]])
        w = want[key]
        got = (r[2].unscaled, r[3].unscaled, r[4].unscaled, r[5].unscaled,
               r[9])
        assert got == w, f"Q1 digest mismatch {r[0]}/{r[1]}: {got} vs {w}"


def q3_oracle(jdata):
    """Exact top-10 (orderkey, revenue_unscaled) for TPC-H Q3."""
    from tidb_tpu.types.value import parse_date

    cutoff = parse_date("1995-03-15")
    segs, ccodes = jdata["customer"]["c_mktsegment"]
    bld = list(segs).index("BUILDING")
    cust = jdata["customer"]["c_custkey"]
    cust_ok = np.zeros(int(cust.max()) + 1, bool)
    cust_ok[cust[np.asarray(ccodes) == bld]] = True
    o = jdata["orders"]
    o_ok = (o["o_orderdate"] < cutoff) & cust_ok[o["o_custkey"]]
    span = int(o["o_orderkey"].max()) + 1
    ok_arr = np.zeros(span, bool)
    ok_arr[o["o_orderkey"][o_ok]] = True
    odate = np.zeros(span, np.int64)
    odate[o["o_orderkey"][o_ok]] = o["o_orderdate"][o_ok]
    li = jdata["lineitem"]
    lm = (li["l_shipdate"] > cutoff) & ok_arr[li["l_orderkey"]]
    rev = np.zeros(span, np.int64)
    np.add.at(rev, li["l_orderkey"][lm],
              li["l_extendedprice"][lm] * (100 - li["l_discount"][lm]))
    nz = np.nonzero(rev)[0]
    top = nz[np.lexsort((nz, odate[nz], -rev[nz]))[:10]]
    return [(int(k), int(rev[k])) for k in top]


def q5_oracle(jdata):
    """Exact (nation, revenue_unscaled) rows for TPC-H Q5 (ASIA/1994)."""
    from tidb_tpu.types.value import parse_date

    d1, d2 = parse_date("1994-01-01"), parse_date("1995-01-01")
    rnames, rcodes = jdata["region"]["r_name"]
    asia = list(rnames).index("ASIA")
    r_ok = np.asarray(rcodes) == asia
    reg_ok = np.zeros(int(jdata["region"]["r_regionkey"].max()) + 1, bool)
    reg_ok[jdata["region"]["r_regionkey"][r_ok]] = True
    nat = jdata["nation"]
    n_ok = reg_ok[nat["n_regionkey"]]
    nspan = int(nat["n_nationkey"].max()) + 1
    nat_ok = np.zeros(nspan, bool)
    nat_ok[nat["n_nationkey"][n_ok]] = True
    cust = jdata["customer"]
    cspan = int(cust["c_custkey"].max()) + 1
    c_nat = np.full(cspan, -1, np.int64)
    c_nat[cust["c_custkey"]] = cust["c_nationkey"]
    supp = jdata["supplier"]
    sspan = int(supp["s_suppkey"].max()) + 1
    s_nat = np.full(sspan, -1, np.int64)
    s_nat[supp["s_suppkey"]] = supp["s_nationkey"]
    o = jdata["orders"]
    o_ok = (o["o_orderdate"] >= d1) & (o["o_orderdate"] < d2)
    ospan = int(o["o_orderkey"].max()) + 1
    o_cnat = np.full(ospan, -1, np.int64)
    o_cnat[o["o_orderkey"][o_ok]] = c_nat[o["o_custkey"][o_ok]]
    li = jdata["lineitem"]
    lnat = s_nat[li["l_suppkey"]]
    onat = o_cnat[li["l_orderkey"]]
    m = (lnat >= 0) & (lnat == onat) & nat_ok[np.clip(lnat, 0, None)]
    rev = np.zeros(nspan, np.int64)
    np.add.at(rev, lnat[m],
              li["l_extendedprice"][m] * (100 - li["l_discount"][m]))
    return {int(k): int(rev[k]) for k in np.nonzero(rev)[0]}


def times(run, repeat) -> list[float]:
    run()  # warm
    ts = []
    for _ in range(repeat):
        t = time.perf_counter()
        run()
        ts.append(time.perf_counter() - t)
    ts.sort()
    return ts


def report(name, ts, rows) -> tuple[str, float]:
    p50 = ts[len(ts) // 2]
    line = (f"{name}: p50={p50 * 1e3:.1f}ms max={ts[-1] * 1e3:.1f}ms "
            f"(of {len(ts)}) {rows / p50 / 1e6:.1f}M rows/s single-stream")
    return line, rows / p50


def main() -> None:
    sf = float(os.environ.get("BENCH_SF", 10))
    join_sf = float(os.environ.get("BENCH_JOIN_SF", 10))
    ssb_sf = float(os.environ.get("BENCH_SSB_SF", 100))
    cb_rows = int(float(os.environ.get("BENCH_CB_ROWS", 1e8)))
    sf_big = float(os.environ.get("BENCH_SF_BIG", 100))
    repeat = int(os.environ.get("BENCH_REPEAT", 5))
    n_clients = int(os.environ.get("BENCH_CLIENTS", 8))
    platform = os.environ.get("BENCH_PLATFORM")
    if platform:
        import jax
        jax.config.update("jax_platforms", platform)

    from tidb_tpu.bench.tpch import (
        TPCH_Q1,
        TPCH_Q6,
        generate_lineitem_arrays,
        load_lineitem,
    )
    from tidb_tpu.session import Session

    lines: list[str] = []

    # ---- 1. TPC-H SF10 scan flight + interpreted baseline ----
    n10 = int(ROWS_PER_SF * sf)
    t0 = time.perf_counter()
    arrays = generate_lineitem_arrays(n10)
    gen_s = time.perf_counter() - t0
    session = Session()
    t0 = time.perf_counter()
    load_lineitem(session, n10, arrays=arrays)
    log(f"tpch sf{sf:g}: gen={gen_s:.0f}s load="
        f"{time.perf_counter() - t0:.0f}s")
    baseline_rps = interpreted_q6_baseline(arrays)
    got = session.query(TPCH_Q6)[0][0]
    assert got is not None and got.unscaled == q6_oracle(arrays), "q6"
    check_q1(session.query(TPCH_Q1), arrays)
    q6_ts = times(lambda: session.query(TPCH_Q6), repeat)
    q1_ts = times(lambda: session.query(TPCH_Q1), repeat)
    l6, q6_sf10_rps = report(f"q6_sf{sf:g}", q6_ts, n10)
    l1, _ = report(f"q1_sf{sf:g}", q1_ts, n10)
    lines += [l6, l1]

    # concurrent throughput (separate, labeled)
    def throughput(sql, per=2) -> float:
        import threading

        sessions = [Session(session.storage, cop=session.cop)
                    for _ in range(n_clients)]
        for s in sessions:
            s.query(sql)
        errs: list[BaseException] = []

        def run(s):
            try:
                for _ in range(per):
                    s.query(sql)
            except BaseException as e:
                errs.append(e)

        best = 0.0
        for _ in range(2):
            threads = [threading.Thread(target=run, args=(s,))
                       for s in sessions]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            if errs:
                raise errs[0]
            best = max(best, n_clients * per * n10 /
                       (time.perf_counter() - t0))
        return best

    tput = throughput(TPCH_Q6)
    lines.append(f"q6 concurrent throughput ({n_clients} clients): "
                 f"{tput / 1e6:.1f}M rows/s "
                 f"({tput / baseline_rps:.1f}x the interpreted baseline)")
    del session, arrays, throughput  # noqa: F821 - drop the closure too
    gc.collect()
    log("sf10 flight freed")

    # ---- 2. TPC-H join corpus at join_sf ----
    from tidb_tpu.bench.tpch_data import generate_tpch, load_table
    from tidb_tpu.bench.tpch_queries import TPCH_QUERIES

    t0 = time.perf_counter()
    jdata = generate_tpch(join_sf, 11)
    js = Session()
    for t in jdata:
        load_table(js, t, jdata[t])
    jrows = len(jdata["lineitem"]["l_orderkey"])
    log(f"tpch join corpus sf{join_sf:g}: gen+load="
        f"{time.perf_counter() - t0:.0f}s ({jrows} lineitem rows)")
    log("join corpus loaded; computing oracles")
    want3 = q3_oracle(jdata)
    got3 = [(int(r[0]), r[1].unscaled) for r in js.query(
        TPCH_QUERIES["q3"])]
    assert got3 == want3, f"q3 digest: {got3[:3]} vs {want3[:3]}"
    want5 = q5_oracle(jdata)
    got5 = {r[0]: r[1].unscaled for r in js.query(TPCH_QUERIES["q5"])}
    nnames, _ = jdata["nation"]["n_name"]
    nat_by_name = {nm: int(k) for nm, k in zip(
        nnames, jdata["nation"]["n_nationkey"])}
    got5 = {nat_by_name[name]: v for name, v in got5.items()}
    assert got5 == want5, f"q5 digest: {got5} vs {want5}"
    log("join digests OK; timing q3/q5")
    q3_ts = times(lambda: js.query(TPCH_QUERIES["q3"]), repeat)
    q5_ts = times(lambda: js.query(TPCH_QUERIES["q5"]), repeat)
    l3, q3_rps = report(f"q3_sf{join_sf:g}", q3_ts, jrows)
    l5, _ = report(f"q5_sf{join_sf:g}", q5_ts, jrows)
    lines += [l3 + f" ({q3_rps / baseline_rps:.1f}x interpreted baseline)",
              l5]
    del js, jdata
    gc.collect()

    # ---- 3. SSB Q1 flight ----
    from tidb_tpu.bench import ssb

    t0 = time.perf_counter()
    lo = ssb.generate_lineorder(ssb_sf)
    ss = Session()
    nrows_ssb = ssb.load_ssb(ss, ssb_sf, lineorder=lo)
    log(f"ssb sf{ssb_sf:g}: gen+load={time.perf_counter() - t0:.0f}s "
        f"({nrows_ssb} lineorder rows)")
    for q in ("q1.1", "q1.2", "q1.3"):
        got = ss.query(ssb.SSB_QUERIES[q])[0][0]
        assert got is not None and int(got) == ssb.q1_oracle(lo, q), q
        ts = times(lambda sql=ssb.SSB_QUERIES[q]: ss.query(sql), repeat)
        line, _ = report(f"ssb_{q}_sf{ssb_sf:g}", ts, nrows_ssb)
        lines.append(line)
    del ss, lo
    gc.collect()

    # ---- 4. ClickBench-style hits ----
    from tidb_tpu.bench import clickbench as cbench

    t0 = time.perf_counter()
    hits = cbench.generate_hits(cb_rows)
    cs = Session()
    cbench.load_hits(cs, cb_rows, hits=hits)
    log(f"clickbench hits_{cb_rows // 1_000_000}m: gen+load="
        f"{time.perf_counter() - t0:.0f}s")
    for q, sql in cbench.CB_QUERIES.items():
        got = cs.query(sql)
        want = cbench.cb_oracle(hits, q)
        if q in ("cb_scan", "cb_sum"):
            ok = int(got[0][0]) == want
        elif q == "cb_agg":
            ok = (int(got[0][0]), int(got[0][1])) == want
        else:
            ok = [(int(a), int(b)) for a, b in got] == want
        assert ok, f"{q} digest"
        ts = times(lambda s2=sql: cs.query(s2), repeat)
        line, _ = report(q, ts, cb_rows)
        lines.append(line)
    del cs, hits
    gc.collect()

    # ---- 5. North star: TPC-H SF100 Q1/Q6 ----
    headline_rps = q6_sf10_rps
    headline_name = f"q6_sf{sf:g}"
    try:
        nbig = int(ROWS_PER_SF * sf_big)
        t0 = time.perf_counter()
        big_arrays = generate_lineitem_arrays(nbig)
        gen_s = time.perf_counter() - t0
        bs = Session()
        t0 = time.perf_counter()
        load_lineitem(bs, nbig, arrays=big_arrays)
        log(f"tpch sf{sf_big:g}: gen={gen_s:.0f}s load="
            f"{time.perf_counter() - t0:.0f}s ({nbig} rows)")
        got = bs.query(TPCH_Q6)[0][0]
        assert got is not None and got.unscaled == q6_oracle(big_arrays)
        check_q1(bs.query(TPCH_Q1), big_arrays)
        q6b = times(lambda: bs.query(TPCH_Q6), repeat)
        q1b = times(lambda: bs.query(TPCH_Q1), repeat)
        l6b, q6_big_rps = report(f"q6_sf{sf_big:g}", q6b, nbig)
        l1b, _ = report(f"q1_sf{sf_big:g}", q1b, nbig)
        lines += [l6b, l1b]
        headline_rps = q6_big_rps
        headline_name = f"q6_sf{sf_big:g}"
        del bs, big_arrays
        gc.collect()
    except Exception as e:  # report the failure, keep the SF10 headline
        lines.append(f"sf{sf_big:g} flight FAILED: {type(e).__name__}: "
                     f"{str(e)[:200]}")

    print(json.dumps({
        "metric": "tpch_q6_rows_per_sec",
        "value": round(headline_rps),
        "unit": "rows/s",
        "vs_baseline": round(headline_rps / baseline_rps, 2),
    }))
    log(f"headline={headline_name}; basis: single-stream engine vs "
        f"single-stream interpreted row-loop baseline "
        f"({baseline_rps / 1e3:.0f}K rows/s); "
        f"platform={__import__('jax').default_backend()}")
    for ln in lines:
        log(ln)


if __name__ == "__main__":
    main()
