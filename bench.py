"""Benchmark: TPC-H on the TiTPU engine — SF10 Q6/Q1 scans + SF1 Q3 join.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Comparison basis (BASELINE.md): the reference publishes no absolute
numbers in-repo and its Go toolchain isn't present here, so the floor is
a row-at-a-time interpreted coprocessor baseline measured in-process —
the execution model of the reference's mocktikv interpreter (reference:
store/mockstore/mocktikv/cop_handler_dag.go:150, row loop over MVCC
pairs) — timed on a sample and scaled. BOTH sides of the headline ratio
are SINGLE-STREAM: vs_baseline = engine single-stream Q6 rows/s divided
by interpreter rows/s (round-2 verdict asked for an apples-to-apples
basis; concurrent throughput is reported separately on stderr, labeled).

Configs (BASELINE.md table):
  q6_sf10  — scan+filter+SUM over 60M rows (tiled device execution)
  q1_sf10  — scan + 4-group segment aggregation over 60M rows
  q3_sf1   — customer x orders x lineitem snowflake join fragment + hc agg
Correctness gates: Q6/Q1 against exact numpy oracles at full scale; Q3
against the sqlite differential oracle at SF 0.1 (same generator seed
corpus the test suite uses; SF1 timing runs the identical plan shape).

Environment knobs: BENCH_SF (default 10), BENCH_JOIN_SF (default 1.0),
BENCH_REPEAT, BENCH_CLIENTS, BENCH_PLATFORM.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

ROWS_PER_SF = 6_001_215


def interpreted_q6_baseline(arrays: dict[str, np.ndarray],
                            sample: int = 200_000) -> float:
    """Row-at-a-time interpreted Q6 (mocktikv-style) rows/sec.

    Median of 3 runs — a single pass is noisy (GC, turbo, co-tenants) and
    the ratio metric inherits that noise."""
    from tidb_tpu.types.value import parse_date

    n = min(sample, len(arrays["l_shipdate"]))
    ship = arrays["l_shipdate"][:n].tolist()
    disc = arrays["l_discount"][:n].tolist()
    qty = arrays["l_quantity"][:n].tolist()
    price = arrays["l_extendedprice"][:n].tolist()
    d1, d2 = parse_date("1994-01-01"), parse_date("1995-01-01")
    rates = []
    for _ in range(3):
        t0 = time.perf_counter()
        acc = 0
        for i in range(n):
            s = ship[i]
            if s >= d1 and s < d2:
                d = disc[i]
                if 5 <= d <= 7 and qty[i] < 2400:
                    acc += price[i] * d
        dt = time.perf_counter() - t0
        rates.append(n / dt)
    return sorted(rates)[1]


def q6_oracle(arrays) -> int:
    from tidb_tpu.types.value import parse_date

    d1, d2 = parse_date("1994-01-01"), parse_date("1995-01-01")
    m = ((arrays["l_shipdate"] >= d1) & (arrays["l_shipdate"] < d2)
         & (arrays["l_discount"] >= 5) & (arrays["l_discount"] <= 7)
         & (arrays["l_quantity"] < 2400))
    return int((arrays["l_extendedprice"][m].astype(np.int64)
                * arrays["l_discount"][m]).sum())


def q1_oracle(arrays) -> dict[tuple[int, int], tuple[int, ...]]:
    """Exact int64 aggregates per (returnflag, linestatus) group:
    (sum_qty, sum_base, sum_disc_price, sum_charge, count) in unscaled
    decimal units (scales 2, 2, 4, 6)."""
    from tidb_tpu.types.value import parse_date

    cutoff = parse_date("1998-12-01") - 90
    m = arrays["l_shipdate"] <= cutoff
    rf = arrays["l_returnflag"][m]
    ls = arrays["l_linestatus"][m]
    qty = arrays["l_quantity"][m].astype(np.int64)
    ext = arrays["l_extendedprice"][m].astype(np.int64)
    disc = arrays["l_discount"][m].astype(np.int64)
    tax = arrays["l_tax"][m].astype(np.int64)
    key = rf * 2 + ls
    nseg = 6
    out = {}
    for name, vals in (("qty", qty), ("base", ext),
                       ("disc_price", ext * (100 - disc)),
                       ("charge", ext * (100 - disc) * (100 + tax)),
                       ("count", np.ones(len(key), np.int64))):
        acc = np.zeros(nseg, dtype=np.int64)
        np.add.at(acc, key, vals)
        out[name] = acc
    res = {}
    for k in range(nseg):
        if out["count"][k]:
            res[(k // 2, k % 2)] = tuple(int(out[n][k]) for n in (
                "qty", "base", "disc_price", "charge", "count"))
    return res


def check_q1(rows, arrays) -> None:
    """Session Q1 rows vs the exact oracle (integer digests only)."""
    want = q1_oracle(arrays)
    flag_code = {"A": 0, "R": 1, "N": 2}
    status_code = {"F": 0, "O": 1}
    assert len(rows) == len(want), (len(rows), len(want))
    for r in rows:
        key = (flag_code[r[0]], status_code[r[1]])
        w = want[key]
        got = (r[2].unscaled, r[3].unscaled, r[4].unscaled, r[5].unscaled,
               r[9])
        assert got == w, f"Q1 digest mismatch for {r[0]}/{r[1]}: {got} vs {w}"


def verify_q3_sf01() -> None:
    """Differential-check Q3 against sqlite at SF 0.1 (the suite's oracle
    corpus); the SF1 timing below runs the identical plan shape."""
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tests"))
    from tpch_oracle import (load_sqlite, normalize_cell, rows_equal,
                             to_sqlite_sql)

    from tidb_tpu.bench.tpch_data import TPCH_DDL, generate_tpch, load_table
    from tidb_tpu.bench.tpch_queries import TPCH_QUERIES
    from tidb_tpu.session import Session

    s = Session()
    data = generate_tpch(0.1, 11)
    need = ("region", "nation", "customer", "orders", "lineitem")
    for t in need:
        load_table(s, t, data[t])
    conn = load_sqlite({t: data[t] for t in need},
                       {t: TPCH_DDL[t] for t in need})
    sql = TPCH_QUERIES["q3"]
    got = [tuple(normalize_cell(c) for c in r) for r in s.query(sql)]
    want = [tuple(normalize_cell(c) for c in r)
            for r in conn.execute(to_sqlite_sql(sql)).fetchall()]
    ok, why = rows_equal(got, want, ordered=True)
    assert ok, f"Q3 differential failed at SF0.1: {why}"


def main() -> None:
    sf = float(os.environ.get("BENCH_SF", 10))
    join_sf = float(os.environ.get("BENCH_JOIN_SF", 1.0))
    n_rows = int(os.environ.get("BENCH_ROWS", int(ROWS_PER_SF * sf)))
    repeat = int(os.environ.get("BENCH_REPEAT", 5))
    n_clients = int(os.environ.get("BENCH_CLIENTS", 8))
    platform = os.environ.get("BENCH_PLATFORM")
    if platform:
        # this image pre-imports jax at interpreter startup, so
        # JAX_PLATFORMS in the env is ignored; the config path still works
        import jax
        jax.config.update("jax_platforms", platform)

    from tidb_tpu.bench.tpch import (
        TPCH_Q1,
        TPCH_Q6,
        generate_lineitem_arrays,
        load_lineitem,
    )
    from tidb_tpu.session import Session

    t0 = time.perf_counter()
    arrays = generate_lineitem_arrays(n_rows)
    gen_s = time.perf_counter() - t0

    session = Session()
    t0 = time.perf_counter()
    load_lineitem(session, n_rows, arrays=arrays)
    load_s = time.perf_counter() - t0

    baseline_rps = interpreted_q6_baseline(arrays)

    # correctness gates before timing (exact digests vs numpy oracles)
    got = session.query(TPCH_Q6)[0][0]  # warms compile + device tile cache
    assert got is not None and got.unscaled == q6_oracle(arrays), \
        f"Q6 digest mismatch: {got.unscaled} vs {q6_oracle(arrays)}"
    check_q1(session.query(TPCH_Q1), arrays)
    verify_q3_sf01()

    def times(run) -> list[float]:
        run()  # warm
        ts = []
        for _ in range(repeat):
            t = time.perf_counter()
            run()
            ts.append(time.perf_counter() - t)
        ts.sort()
        return ts

    def report(name: str, ts: list[float], rows: int) -> str:
        p50 = ts[len(ts) // 2]
        worst = ts[-1]
        return (f"{name}: p50={p50 * 1e3:.1f}ms max={worst * 1e3:.1f}ms "
                f"(of {len(ts)}) {rows / p50 / 1e6:.1f}M rows/s "
                f"single-stream")

    q6_ts = times(lambda: session.query(TPCH_Q6))
    q1_ts = times(lambda: session.query(TPCH_Q1))

    # join config: full snowflake fragment at SF1 (separate storage)
    from tidb_tpu.bench.tpch_data import TPCH_DDL, generate_tpch, load_table
    from tidb_tpu.bench.tpch_queries import TPCH_QUERIES

    js = Session()
    t0 = time.perf_counter()
    jdata = generate_tpch(join_sf, 11)
    for t in ("region", "nation", "customer", "orders", "lineitem"):
        load_table(js, t, jdata[t])
    jload_s = time.perf_counter() - t0
    jrows = len(jdata["lineitem"]["l_orderkey"])
    q3_ts = times(lambda: js.query(TPCH_QUERIES["q3"]))

    # concurrent throughput (separate, labeled: N clients pipelining on
    # the dispatch round-trip vs the single-threaded interpreter)
    def throughput(sql: str, per: int = 2) -> float:
        import threading

        sessions = [Session(session.storage, cop=session.cop)
                    for _ in range(n_clients)]
        for s in sessions:
            s.query(sql)
        errs: list[BaseException] = []

        def run(s):
            try:
                for _ in range(per):
                    s.query(sql)
            except BaseException as e:
                errs.append(e)

        best = 0.0
        for _ in range(2):
            threads = [threading.Thread(target=run, args=(s,))
                       for s in sessions]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            dt = time.perf_counter() - t0
            if errs:
                raise errs[0]
            best = max(best, n_clients * per * n_rows / dt)
        return best

    q6_tput = throughput(TPCH_Q6)

    q6_p50 = q6_ts[len(q6_ts) // 2]
    single_stream_rps = n_rows / q6_p50
    print(json.dumps({
        "metric": "tpch_q6_rows_per_sec",
        "value": round(single_stream_rps),
        "unit": "rows/s",
        "vs_baseline": round(single_stream_rps / baseline_rps, 2),
    }))
    # context on stderr so the JSON line stays clean
    print(
        f"# basis: single-stream engine vs single-stream interpreted "
        f"row-loop baseline ({baseline_rps / 1e3:.0f}K rows/s); "
        f"platform={__import__('jax').default_backend()}\n"
        f"# lineitem SF{sf:g} ({n_rows} rows, gen={gen_s:.0f}s "
        f"load={load_s:.0f}s) | join corpus SF{join_sf:g} "
        f"({jrows} lineitem rows, load={jload_s:.0f}s)\n"
        f"# {report(f'q6_sf{sf:g}', q6_ts, n_rows)}\n"
        f"# {report(f'q1_sf{sf:g}', q1_ts, n_rows)}\n"
        f"# {report(f'q3_sf{join_sf:g}', q3_ts, jrows)}\n"
        f"# q6 concurrent throughput ({n_clients} clients): "
        f"{q6_tput / 1e6:.1f}M rows/s "
        f"({q6_tput / baseline_rps:.1f}x the single-threaded baseline; "
        f"round-trips pipeline across clients)",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
