"""Benchmark board: TPC-H (SF10 + SF100), SSB, ClickBench-style configs.

Prints the headline JSON line {"metric", "value", "unit", "vs_baseline"}
to stdout — INCREMENTALLY: once after every completed flight (latest line
supersedes earlier ones), so a later flight's failure can never erase the
board. The headline is TPC-H Q6 at the north-star SF100 scale
(BASELINE.json metric: "TPC-H rows/sec/chip; Q1+Q6 p50 latency at SF100").

Isolation: each flight runs in its OWN SUBPROCESS. The parent holds only
numpy and a few MB; a flight that exhausts RAM is the biggest process on
the box, so the OOM killer takes the flight, not the board (round 4
lesson: one in-process SSB SF100 flight OOM-killed the whole board,
BENCH_r04.json rc=137). Flights auto-scale their dataset to MemAvailable.

Comparison basis (BASELINE.md): the reference publishes no absolute
numbers in-repo and its Go toolchain isn't present here, so the floor is
a COMPILED (C++ -O3) row-at-a-time Q6 loop over row-major storage — the
execution model of the reference's mocktikv interpreter (reference:
store/mockstore/mocktikv/cop_handler_dag.go:150, row loop over MVCC
pairs) without its per-row decode overhead, i.e. a conservative floor
(native/baseline.cpp). The old Python row-loop baseline is still measured
and reported for series continuity with BENCH_r01..r04. BOTH sides of
the headline ratio are SINGLE-STREAM.

Configs (BASELINE.json configs[0..4] + the r04 join target):
  q6_sf10 / q1_sf10     — scan flight at SF10 (series continuity)
  q6_sf100 / q1_sf100   — the north star (BENCH_SF_BIG, default 100)
  q3_sf10 / q5_sf10     — snowflake join fragments at SF10
  ssb q1.1-1.3          — SSB flight at BENCH_SSB_SF (default 100)
  cb_*                  — ClickBench-style wide scan/TopN at
                          BENCH_CB_ROWS (default 100M)
  multichip             — mesh data plane: sharded-vs-single-device
                          rows/s + per-device placement (shard spec,
                          bytes per device) at BENCH_MESH_ROWS rows
                          over BENCH_MESH_DEVICES devices

Every timed query passes an exact digest check against a numpy oracle
first. Each timed query's per-operator/per-stage attribution (the Top
SQL plane's session-side read: stages_ms / operators_ms / operator
transfer bytes) is logged as an `attribution <name>: {...}` line and
stored under the flight result's "attribution" key, and every datagen/
load phase emits a heartbeat (rows, rows/s, RSS) every 5s — so an OOM
or timeout kill leaves a diagnosable trail. On any flight failure the
child persists an inspection snapshot (res["inspection"]: the
obs_inspect rules over every live store + event-ring tails) into the
result JSON, and a partial snapshot is re-dumped every 30s so even a
SIGKILL'd flight (rc=137/rc=124) leaves a diagnosis. The SF100
north-star flight (tpch_big) runs FIRST. Environment knobs:
BENCH_SF (10), BENCH_JOIN_SF (10),
BENCH_SSB_SF (100), BENCH_CB_ROWS (1e8), BENCH_SF_BIG (100),
BENCH_MESH_ROWS (4e6), BENCH_MESH_DEVICES (8),
BENCH_REPEAT (5), BENCH_CLIENTS (8), BENCH_PLATFORM,
BENCH_FLIGHT_TIMEOUT (5400s), BENCH_RAM_FRACTION (0.75),
BENCH_FLIGHTS (comma list to run a subset).
"""

from __future__ import annotations

import ctypes
import json
import os
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np

ROWS_PER_SF = 6_001_215

# lineitem physical column order (matches bench.tpch.LINEITEM_DDL)
_LI_COLS = [
    "l_orderkey", "l_partkey", "l_suppkey", "l_linenumber", "l_quantity",
    "l_extendedprice", "l_discount", "l_tax", "l_returnflag",
    "l_linestatus", "l_shipdate", "l_commitdate", "l_receiptdate",
]


def _meminfo_gb(field: str) -> float:
    try:
        with open("/proc/meminfo") as f:
            for ln in f:
                if ln.startswith(field):
                    return int(ln.split()[1]) / 1e6
    except OSError:
        pass
    return 0.0


def _rss_gb() -> float:
    try:
        with open("/proc/self/status") as f:
            for ln in f:
                if ln.startswith("VmRSS"):
                    return int(ln.split()[1]) / 1e6
    except OSError:
        pass
    return 0.0


def log(msg: str) -> None:
    print(f"# [rss={_rss_gb():.1f}G] {msg}", file=sys.stderr, flush=True)


class _Heartbeat:
    """Datagen/load heartbeat: a daemon thread logs progress (rows so
    far, rows/s, process RSS) every few seconds, so the next SF100
    OOM kill or timeout (BENCH_r04 rc=137 at gen, BENCH_r05 rc=124 at
    504s/45.9G RSS) leaves a diagnosable trail in the board output
    instead of a silent death. Flights bump `.rows` as they generate;
    phases that cannot count rows still get elapsed + RSS."""

    def __init__(self, label: str, interval_s: float = 5.0) -> None:
        self.label = label
        self.interval_s = interval_s
        self.rows = 0
        self.t0 = time.perf_counter()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="bench-heartbeat")

    def _line(self, tag: str) -> None:
        el = time.perf_counter() - self.t0
        rate = self.rows / el if el > 0 else 0.0
        log(f"heartbeat {self.label} {tag}: rows={self.rows} "
            f"({rate / 1e6:.2f}M rows/s, {el:.0f}s elapsed)")

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self._line("tick")

    def __enter__(self) -> "_Heartbeat":
        self._thread.start()
        return self

    def __exit__(self, *exc) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)
        self._line("done" if exc[0] is None else "ABORTED")


GEN_VERSION = 1  # bump to invalidate on-disk datagen caches


def _cache_dir() -> str:
    return os.environ.get(
        "BENCH_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".bench_cache"))


def generate_lineitem_chunked(n: int, hb: _Heartbeat,
                              chunk: int = 16_000_000):
    """Chunked lineitem generation, streamed through an on-disk columnar
    cache (one .npy per column under BENCH_CACHE_DIR) reused across
    runs.

    The SF100 flights died in datagen two rounds running (BENCH_r04
    rc=137 OOM, r05 rc=124 timeout at 504s/45.9G RSS, all 600M rows
    held in memory): generation now writes each chunk straight into
    np.lib.format memmaps — transient RSS is ONE chunk of generator
    temporaries, the kernel flushes column pages behind the writer —
    and a later run finds the `_COMPLETE` marker and memory-maps the
    columns read-only in O(seconds) with page-cache-evictable RSS.
    Chunks are seeded independently — self-consistent data; the oracles
    read the same (mapped) arrays. Falls back to in-memory generation
    when the cache dir is unwritable."""
    from tidb_tpu.bench.tpch import generate_lineitem_arrays

    if n <= chunk:
        out = generate_lineitem_arrays(n)
        hb.rows = n
        return out
    # chunk is part of the identity: chunks are seeded independently, so
    # the concrete rows are a function of the chunk size
    tag = os.path.join(_cache_dir(),
                       f"lineitem_n{n}_c{chunk}_seed42_v{GEN_VERSION}")
    marker = os.path.join(tag, "_COMPLETE")
    if os.path.exists(marker):
        out = {c: np.load(os.path.join(tag, c + ".npy"), mmap_mode="r")
               for c in _LI_COLS}
        hb.rows = n
        log(f"datagen cache HIT: {tag} ({n} rows mapped)")
        return out
    first = generate_lineitem_arrays(chunk, seed=42)
    try:
        os.makedirs(tag, exist_ok=True)
        out = {k: np.lib.format.open_memmap(
            os.path.join(tag, k + ".npy"), mode="w+", dtype=v.dtype,
            shape=(n,)) for k, v in first.items()}
        cached = True
    except OSError as e:
        log(f"datagen cache unavailable ({e}); generating in memory")
        out = {k: np.empty(n, dtype=v.dtype) for k, v in first.items()}
        cached = False
    lo = 0
    i = 0
    while lo < n:
        hi = min(lo + chunk, n)
        part = first if lo == 0 else \
            generate_lineitem_arrays(hi - lo, seed=42 + i)
        for k in part:
            out[k][lo:hi] = part[k]
        part = None
        if i == 0:
            first = None
        hb.rows = hi
        lo = hi
        i += 1
    if cached:
        for v in out.values():
            v.flush()
        with open(marker, "w") as f:
            f.write(f"{n}\n")
        log(f"datagen cache WRITTEN: {tag}")
        # reopen read-only: the loaded epochs then share the page cache
        # and a crashed later phase cannot corrupt the cache
        out = {c: np.load(os.path.join(tag, c + ".npy"), mmap_mode="r")
               for c in _LI_COLS}
    return out


def _attribution(session) -> dict:
    """The last timed run's per-stage/per-operator attribution (the
    session-side read of the Top SQL plane) — persisted per query into
    the flight result + board tail so BENCH_*.json explains where the
    milliseconds went, not only how many there were. `engines` is the
    device/host path decision per coprocessor read, with the fragment
    mode and any gate reason embedded ("device[fat]@mesh8",
    "host(fragment:key-span)") — a regression off the device path now
    names itself on the board."""
    return {
        "stages_ms": {k: round(v * 1e3, 3)
                      for k, v in session.last_stages.items()},
        "operators_ms": {k: round(v * 1e3, 3)
                         for k, v in session.last_op_wall.items()},
        "operator_stages_ms": {
            op: {k: round(v * 1e3, 3) for k, v in d.items()}
            for op, d in session.last_op_stages.items()},
        "operator_bytes": dict(session.last_op_bytes),
        "engines": list(getattr(session, "last_engines", ()) or ()),
    }


def note_attribution(res: dict, name: str, session) -> None:
    att = _attribution(session)
    res.setdefault("attribution", {})[name] = att
    log(f"attribution {name}: " + json.dumps(att, sort_keys=True))
    paths = sorted(set(att["engines"]))
    host = [e for e in paths if e.startswith("host")]
    res["lines"].append(
        f"path {name}: {','.join(paths) or '(none)'}"
        + (" <- HOST FALLBACK" if host else ""))


# ---------------------------------------------------------------------------
# Baselines (parent-side: numpy + ctypes only, no jax import)
# ---------------------------------------------------------------------------

def _load_baseline_lib():
    so = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "native", "libbaseline.so")
    try:  # no-op when fresh; rebuilds after baseline.cpp edits
        subprocess.run(["make", "-C", os.path.dirname(so), "libbaseline.so"],
                       check=True, capture_output=True, timeout=120)
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired,
            OSError):
        if not os.path.exists(so):
            raise
    lib = ctypes.CDLL(so)
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    lib.q6_kv_rowloop.restype = ctypes.c_double
    lib.q6_kv_rowloop.argtypes = [
        i64p, ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_int64,
        ctypes.c_int64, ctypes.POINTER(ctypes.c_int64)]
    lib.q6_columnar_rowloop.restype = ctypes.c_double
    lib.q6_columnar_rowloop.argtypes = [
        i64p, i64p, i64p, i64p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.POINTER(ctypes.c_int64)]
    lib.q1_kv_rowloop.restype = ctypes.c_double
    lib.q1_kv_rowloop.argtypes = [
        i64p, ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int64,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")]
    return lib


def compiled_baselines(arrays, sample: int = 6_000_000):
    """(q6_kv_rps, q6_columnar_rps, q1_kv_rps) from native/baseline.cpp,
    median of 3 runs each over a `sample`-row prefix. The q6 kv variant
    is the vs_baseline denominator: a compiled row-loop over row-major
    rows, the mocktikv execution model (cop_handler_dag.go:150) minus
    its decode cost — i.e. a floor that flatters the reference."""
    from tidb_tpu.types.value import parse_date

    lib = _load_baseline_lib()
    n = min(sample, len(arrays["l_shipdate"]))
    rows = np.empty((n, len(_LI_COLS)), dtype=np.int64)
    for i, c in enumerate(_LI_COLS):
        rows[:, i] = arrays[c][:n]
    ship, disc = _LI_COLS.index("l_shipdate"), _LI_COLS.index("l_discount")
    qty, price = _LI_COLS.index("l_quantity"), _LI_COLS.index(
        "l_extendedprice")
    d1, d2 = parse_date("1994-01-01"), parse_date("1995-01-01")
    out = ctypes.c_int64()
    want = q6_oracle({k: arrays[k][:n] for k in (
        "l_shipdate", "l_discount", "l_quantity", "l_extendedprice")})
    kv = sorted(lib.q6_kv_rowloop(rows, n, len(_LI_COLS), ship, disc, qty,
                                  price, d1, d2, ctypes.byref(out))
                for _ in range(3))[1]
    assert out.value == want, "compiled kv baseline digest"
    # generator columns may be narrowed (int8/int16/int32 staging); the
    # C loop's ABI is int64 pointers
    cship = np.ascontiguousarray(arrays["l_shipdate"][:n], dtype=np.int64)
    cdisc = np.ascontiguousarray(arrays["l_discount"][:n], dtype=np.int64)
    cqty = np.ascontiguousarray(arrays["l_quantity"][:n], dtype=np.int64)
    cprice = np.ascontiguousarray(
        arrays["l_extendedprice"][:n], dtype=np.int64)
    col = sorted(lib.q6_columnar_rowloop(cship, cdisc, cqty, cprice, n,
                                         d1, d2, ctypes.byref(out))
                 for _ in range(3))[1]
    assert out.value == want, "compiled columnar baseline digest"
    cutoff = parse_date("1998-12-01") - 90
    acc = np.zeros(30, dtype=np.int64)
    q1 = sorted(lib.q1_kv_rowloop(
        rows, n, len(_LI_COLS), ship, _LI_COLS.index("l_returnflag"),
        _LI_COLS.index("l_linestatus"), qty, price, disc,
        _LI_COLS.index("l_tax"), cutoff, acc) for _ in range(3))[1]
    w1 = q1_oracle({k: arrays[k][:n] for k in (
        "l_shipdate", "l_returnflag", "l_linestatus", "l_quantity",
        "l_extendedprice", "l_discount", "l_tax")})
    got1 = {(k // 2, k % 2): tuple(int(v) for v in acc[k * 5:k * 5 + 5])
            for k in range(6) if acc[k * 5 + 4]}
    assert got1 == w1, "compiled q1 baseline digest"
    return n / kv, n / col, n / q1


def interpreted_q6_baseline(arrays, sample: int = 200_000) -> float:
    """Row-at-a-time *Python* interpreted Q6 rows/sec (median of 3) —
    the BENCH_r01..r04 denominator, kept for series continuity."""
    from tidb_tpu.types.value import parse_date

    n = min(sample, len(arrays["l_shipdate"]))
    ship = arrays["l_shipdate"][:n].tolist()
    disc = arrays["l_discount"][:n].tolist()
    qty = arrays["l_quantity"][:n].tolist()
    price = arrays["l_extendedprice"][:n].tolist()
    d1, d2 = parse_date("1994-01-01"), parse_date("1995-01-01")
    rates = []
    for _ in range(3):
        t0 = time.perf_counter()
        acc = 0
        for i in range(n):
            s = ship[i]
            if s >= d1 and s < d2:
                d = disc[i]
                if 5 <= d <= 7 and qty[i] < 2400:
                    acc += price[i] * d
        rates.append(n / (time.perf_counter() - t0))
    return sorted(rates)[1]


# ---------------------------------------------------------------------------
# Oracles (chunked: SF100 masks of 600M rows must not clone the table)
# ---------------------------------------------------------------------------

def q6_oracle(arrays) -> int:
    from tidb_tpu.types.value import parse_date

    d1, d2 = parse_date("1994-01-01"), parse_date("1995-01-01")
    total, n = 0, len(arrays["l_shipdate"])
    for lo in range(0, n, 50_000_000):
        sl = slice(lo, min(lo + 50_000_000, n))
        ship = arrays["l_shipdate"][sl]
        m = ((ship >= d1) & (ship < d2)
             & (arrays["l_discount"][sl] >= 5)
             & (arrays["l_discount"][sl] <= 7)
             & (arrays["l_quantity"][sl] < 2400))
        total += int((arrays["l_extendedprice"][sl][m].astype(np.int64)
                      * arrays["l_discount"][sl][m]).sum())
    return total


def q1_oracle(arrays):
    """Exact int64 aggregates per (returnflag, linestatus) group,
    computed in 50M-row chunks (SF100: a 98%-selective mask must not
    materialise masked copies of the whole table)."""
    from tidb_tpu.types.value import parse_date

    cutoff = parse_date("1998-12-01") - 90
    n = len(arrays["l_shipdate"])
    acc = {name: np.zeros(6, dtype=np.int64)
           for name in ("qty", "base", "disc_price", "charge", "count")}
    for lo in range(0, n, 50_000_000):
        sl = slice(lo, min(lo + 50_000_000, n))
        m = arrays["l_shipdate"][sl] <= cutoff
        key = arrays["l_returnflag"][sl][m] * 2 + \
            arrays["l_linestatus"][sl][m]
        qty = arrays["l_quantity"][sl][m].astype(np.int64)
        ext = arrays["l_extendedprice"][sl][m].astype(np.int64)
        disc = arrays["l_discount"][sl][m].astype(np.int64)
        tax = arrays["l_tax"][sl][m].astype(np.int64)
        for name, vals in (("qty", qty), ("base", ext),
                           ("disc_price", ext * (100 - disc)),
                           ("charge", ext * (100 - disc) * (100 + tax)),
                           ("count", np.ones(len(key), np.int64))):
            np.add.at(acc[name], key, vals)
    res = {}
    for k in range(6):
        if acc["count"][k]:
            res[(k // 2, k % 2)] = tuple(int(acc[nm][k]) for nm in (
                "qty", "base", "disc_price", "charge", "count"))
    return res


def check_q1(rows, arrays) -> None:
    want = q1_oracle(arrays)
    flag_code = {"A": 0, "R": 1, "N": 2}
    status_code = {"F": 0, "O": 1}
    assert len(rows) == len(want), (len(rows), len(want))
    for r in rows:
        key = (flag_code[r[0]], status_code[r[1]])
        w = want[key]
        got = (r[2].unscaled, r[3].unscaled, r[4].unscaled, r[5].unscaled,
               r[9])
        assert got == w, f"Q1 digest mismatch {r[0]}/{r[1]}: {got} vs {w}"


def q3_oracle(jdata):
    """Exact top-10 (orderkey, revenue_unscaled) for TPC-H Q3."""
    from tidb_tpu.types.value import parse_date

    cutoff = parse_date("1995-03-15")
    segs, ccodes = jdata["customer"]["c_mktsegment"]
    bld = list(segs).index("BUILDING")
    cust = jdata["customer"]["c_custkey"]
    cust_ok = np.zeros(int(cust.max()) + 1, bool)
    cust_ok[cust[np.asarray(ccodes) == bld]] = True
    o = jdata["orders"]
    o_ok = (o["o_orderdate"] < cutoff) & cust_ok[o["o_custkey"]]
    span = int(o["o_orderkey"].max()) + 1
    ok_arr = np.zeros(span, bool)
    ok_arr[o["o_orderkey"][o_ok]] = True
    odate = np.zeros(span, np.int64)
    odate[o["o_orderkey"][o_ok]] = o["o_orderdate"][o_ok]
    li = jdata["lineitem"]
    lm = (li["l_shipdate"] > cutoff) & ok_arr[li["l_orderkey"]]
    rev = np.zeros(span, np.int64)
    np.add.at(rev, li["l_orderkey"][lm],
              li["l_extendedprice"][lm] * (100 - li["l_discount"][lm]))
    nz = np.nonzero(rev)[0]
    top = nz[np.lexsort((nz, odate[nz], -rev[nz]))[:10]]
    return [(int(k), int(rev[k])) for k in top]


def q10_oracle(jdata):
    """Exact (custkey, revenue_unscaled) top-20 set for TPC-H Q10."""
    from tidb_tpu.types.value import parse_date

    d1, d2 = parse_date("1993-10-01"), parse_date("1994-01-01")
    o = jdata["orders"]
    o_ok = (o["o_orderdate"] >= d1) & (o["o_orderdate"] < d2)
    ospan = int(o["o_orderkey"].max()) + 1
    o_cust = np.full(ospan, -1, np.int64)
    o_cust[o["o_orderkey"][o_ok]] = o["o_custkey"][o_ok]
    li = jdata["lineitem"]
    rvocab, rcodes = li["l_returnflag"]
    r_code = list(rvocab).index("R")
    cust = o_cust[li["l_orderkey"]]
    m = (np.asarray(rcodes) == r_code) & (cust >= 0)
    cspan = int(jdata["customer"]["c_custkey"].max()) + 1
    rev = np.zeros(cspan, np.int64)
    np.add.at(rev, cust[m],
              li["l_extendedprice"][m] * (100 - li["l_discount"][m]))
    nz = np.nonzero(rev)[0]
    top = nz[np.lexsort((nz, -rev[nz]))[:20]]
    # revenue-only ORDER BY: ties leave the tail unordered, so digests
    # compare the (custkey, revenue) SET
    return {(int(k), int(rev[k])) for k in top}


def time_q10(res: dict, session, jdata, label: str, repeat: int):
    """Digest-check + time TPC-H Q10 (the fused join+agg+topn shape) on
    an already-loaded session; returns rows/s."""
    from tidb_tpu.bench.tpch_queries import TPCH_QUERIES

    want = q10_oracle(jdata)
    got = {(int(r[0]), r[2].unscaled)
           for r in session.query(TPCH_QUERIES["q10"])}
    assert got == want, f"q10 digest: {sorted(got)[:3]} vs " \
                        f"{sorted(want)[:3]}"
    ts = times(lambda: session.query(TPCH_QUERIES["q10"]), repeat)
    note_attribution(res, label, session)
    line, rps = report(label, ts, len(jdata["lineitem"]["l_orderkey"]))
    res["lines"].append(line)
    return rps


def _years_of(days: np.ndarray) -> np.ndarray:
    return days.astype("datetime64[D]").astype(
        "datetime64[Y]").astype(np.int64) + 1970


def _keymap(keys: np.ndarray, vals: np.ndarray) -> np.ndarray:
    out = np.full(int(keys.max()) + 1, -1, np.int64)
    out[keys] = vals
    return out


def q7_oracle(jdata):
    """Exact (supp_nation, cust_nation, year, revenue_unscaled) rows for
    TPC-H Q7 (FRANCE/GERMANY, 1995-1996)."""
    from tidb_tpu.types.value import parse_date

    nvocab, ncodes = jdata["nation"]["n_name"]
    name_of = _keymap(jdata["nation"]["n_nationkey"], np.asarray(ncodes))
    fr, ge = list(nvocab).index("FRANCE"), list(nvocab).index("GERMANY")
    s_nat = _keymap(jdata["supplier"]["s_suppkey"],
                    jdata["supplier"]["s_nationkey"])
    c_nat = _keymap(jdata["customer"]["c_custkey"],
                    jdata["customer"]["c_nationkey"])
    o_cust = _keymap(jdata["orders"]["o_orderkey"],
                     jdata["orders"]["o_custkey"])
    li = jdata["lineitem"]
    d1, d2 = parse_date("1995-01-01"), parse_date("1996-12-31")
    ship = li["l_shipdate"]
    sn = name_of[s_nat[li["l_suppkey"]]]
    cn = name_of[c_nat[o_cust[li["l_orderkey"]]]]
    m = (ship >= d1) & (ship <= d2) & \
        (((sn == fr) & (cn == ge)) | ((sn == ge) & (cn == fr)))
    year = _years_of(ship[m])
    vol = li["l_extendedprice"][m] * (100 - li["l_discount"][m])
    key = (sn[m] * 2 + (cn[m] == fr)) * 8192 + year
    uniq, inv = np.unique(key, return_inverse=True)
    rev = np.zeros(len(uniq), np.int64)
    np.add.at(rev, inv, vol)
    out = set()
    for k, r in zip(uniq, rev):
        year = int(k % 8192)
        sc = int(k // 8192) // 2
        cc = fr if (k // 8192) % 2 else ge
        out.add((nvocab[sc], nvocab[cc], year, int(r)))
    return out


def time_q7(res: dict, session, jdata, label: str, repeat: int):
    """Digest-check + time TPC-H Q7 (the EXTRACT-year grouped
    aggregation newly device-resident in round 14b); returns rows/s."""
    from tidb_tpu.bench.tpch_queries import TPCH_QUERIES

    want = q7_oracle(jdata)
    got = {(r[0], r[1], int(r[2]), r[3].unscaled)
           for r in session.query(TPCH_QUERIES["q7"])}
    assert got == want, f"q7 digest: {sorted(got)[:2]} vs " \
                        f"{sorted(want)[:2]}"
    ts = times(lambda: session.query(TPCH_QUERIES["q7"]), repeat)
    note_attribution(res, label, session)
    line, rps = report(label, ts, len(jdata["lineitem"]["l_orderkey"]))
    res["lines"].append(line)
    return rps


def q8_oracle(jdata):
    """Exact (o_year, mkt_share_unscaled) rows for TPC-H Q8 (AMERICA /
    BRAZIL / ECONOMY ANODIZED STEEL), mkt_share via the engine's own
    decimal division semantics (scale + div_precincrement)."""
    from tidb_tpu.types.value import Decimal, parse_date

    rvocab, rcodes = jdata["region"]["r_name"]
    am = list(rvocab).index("AMERICA")
    reg_ok = np.zeros(int(jdata["region"]["r_regionkey"].max()) + 1, bool)
    reg_ok[jdata["region"]["r_regionkey"][np.asarray(rcodes) == am]] = True
    nat = jdata["nation"]
    nvocab, ncodes = nat["n_name"]
    br = list(nvocab).index("BRAZIL")
    nat_in_am = _keymap(nat["n_nationkey"],
                        reg_ok[nat["n_regionkey"]].astype(np.int64))
    name_of = _keymap(nat["n_nationkey"], np.asarray(ncodes))
    pvocab, pcodes = jdata["part"]["p_type"]
    steel = list(pvocab).index("ECONOMY ANODIZED STEEL")
    p_ok = _keymap(jdata["part"]["p_partkey"],
                   (np.asarray(pcodes) == steel).astype(np.int64))
    s_nat = _keymap(jdata["supplier"]["s_suppkey"],
                    jdata["supplier"]["s_nationkey"])
    c_nat = _keymap(jdata["customer"]["c_custkey"],
                    jdata["customer"]["c_nationkey"])
    o = jdata["orders"]
    d1, d2 = parse_date("1995-01-01"), parse_date("1996-12-31")
    o_ok = (o["o_orderdate"] >= d1) & (o["o_orderdate"] <= d2)
    o_cust = _keymap(o["o_orderkey"],
                     np.where(o_ok, o["o_custkey"], -1))
    o_year = _keymap(o["o_orderkey"], _years_of(o["o_orderdate"]))
    li = jdata["lineitem"]
    cust = o_cust[li["l_orderkey"]]
    m = (p_ok[li["l_partkey"]] == 1) & (cust >= 0) & \
        (nat_in_am[c_nat[np.maximum(cust, 0)]] == 1)
    vol = li["l_extendedprice"][m] * (100 - li["l_discount"][m])
    year = o_year[li["l_orderkey"]][m]
    brazil = name_of[s_nat[li["l_suppkey"]]][m] == br
    out = set()
    for y in np.unique(year):
        ym = year == y
        den = int(vol[ym].sum())
        num = int(vol[ym & brazil].sum())
        # the engine's exact decimal `/` (npeval op "div"): scale 4
        # operands -> scale 8 result, half away from zero
        q, r = divmod(abs(num) * 10 ** 8, abs(den))
        q += 2 * r >= abs(den)
        out.add((int(y), -q if (num < 0) != (den < 0) else q))
    return out


def time_q8(res: dict, session, jdata, label: str, repeat: int):
    """Digest-check + time TPC-H Q8; returns rows/s."""
    from tidb_tpu.bench.tpch_queries import TPCH_QUERIES

    want = q8_oracle(jdata)
    got = {(int(r[0]), r[1].unscaled)
           for r in session.query(TPCH_QUERIES["q8"])}
    assert got == want, f"q8 digest: {sorted(got)[:2]} vs " \
                        f"{sorted(want)[:2]}"
    ts = times(lambda: session.query(TPCH_QUERIES["q8"]), repeat)
    note_attribution(res, label, session)
    line, rps = report(label, ts, len(jdata["lineitem"]["l_orderkey"]))
    res["lines"].append(line)
    return rps


def q5_oracle(jdata):
    """Exact (nation, revenue_unscaled) rows for TPC-H Q5 (ASIA/1994)."""
    from tidb_tpu.types.value import parse_date

    d1, d2 = parse_date("1994-01-01"), parse_date("1995-01-01")
    rnames, rcodes = jdata["region"]["r_name"]
    asia = list(rnames).index("ASIA")
    r_ok = np.asarray(rcodes) == asia
    reg_ok = np.zeros(int(jdata["region"]["r_regionkey"].max()) + 1, bool)
    reg_ok[jdata["region"]["r_regionkey"][r_ok]] = True
    nat = jdata["nation"]
    n_ok = reg_ok[nat["n_regionkey"]]
    nspan = int(nat["n_nationkey"].max()) + 1
    nat_ok = np.zeros(nspan, bool)
    nat_ok[nat["n_nationkey"][n_ok]] = True
    cust = jdata["customer"]
    cspan = int(cust["c_custkey"].max()) + 1
    c_nat = np.full(cspan, -1, np.int64)
    c_nat[cust["c_custkey"]] = cust["c_nationkey"]
    supp = jdata["supplier"]
    sspan = int(supp["s_suppkey"].max()) + 1
    s_nat = np.full(sspan, -1, np.int64)
    s_nat[supp["s_suppkey"]] = supp["s_nationkey"]
    o = jdata["orders"]
    o_ok = (o["o_orderdate"] >= d1) & (o["o_orderdate"] < d2)
    ospan = int(o["o_orderkey"].max()) + 1
    o_cnat = np.full(ospan, -1, np.int64)
    o_cnat[o["o_orderkey"][o_ok]] = c_nat[o["o_custkey"][o_ok]]
    li = jdata["lineitem"]
    lnat = s_nat[li["l_suppkey"]]
    onat = o_cnat[li["l_orderkey"]]
    m = (lnat >= 0) & (lnat == onat) & nat_ok[np.clip(lnat, 0, None)]
    rev = np.zeros(nspan, np.int64)
    np.add.at(rev, lnat[m],
              li["l_extendedprice"][m] * (100 - li["l_discount"][m]))
    return {int(k): int(rev[k]) for k in np.nonzero(rev)[0]}


# ---------------------------------------------------------------------------
# Flight harness
# ---------------------------------------------------------------------------

def times(run, repeat) -> list[float]:
    run()  # warm
    ts = []
    for _ in range(repeat):
        t = time.perf_counter()
        run()
        ts.append(time.perf_counter() - t)
    ts.sort()
    return ts


def report(name, ts, rows) -> tuple[str, float]:
    p50 = ts[len(ts) // 2]
    line = (f"{name}: p50={p50 * 1e3:.1f}ms max={ts[-1] * 1e3:.1f}ms "
            f"(of {len(ts)}) {rows / p50 / 1e6:.1f}M rows/s single-stream")
    return line, rows / p50


def _scale_to_ram(requested_rows: int, bytes_per_row: float,
                  label: str, lines: list[str]) -> int:
    """Cap a dataset to MemAvailable * BENCH_RAM_FRACTION."""
    frac = float(os.environ.get("BENCH_RAM_FRACTION", 0.75))
    avail = _meminfo_gb("MemAvailable") * 1e9
    cap = int(avail * frac / bytes_per_row)
    if cap <= 0:  # /proc/meminfo unreadable: unknown, keep requested
        return requested_rows
    if requested_rows > cap:
        lines.append(
            f"{label}: auto-scaled {requested_rows} -> {cap} rows "
            f"(MemAvailable={avail / 1e9:.0f}GB x {frac} / "
            f"{bytes_per_row:.0f}B/row)")
        return cap
    return requested_rows


def _session_env():
    """Flight-local engine setup: quiet the slow log (it drowned the
    r04 board's output tail), pick the platform."""
    import logging

    logging.getLogger("tidb_tpu.slowlog").setLevel(logging.ERROR)
    platform = os.environ.get("BENCH_PLATFORM")
    if platform:
        import jax
        jax.config.update("jax_platforms", platform)


def _hbm_line(name: str, p50: float, n: int, col_bytes: float) -> str:
    """Estimated device bytes touched per pass vs nominal HBM bandwidth.
    col_bytes = per-row data bytes at staged (narrowed) widths; each
    staged column also carries a 1-byte validity lane + one shared
    visibility lane."""
    import jax

    bw = {"tpu": 819e9}.get(jax.default_backend())  # v5e: ~819 GB/s
    touched = n * col_bytes
    line = (f"{name}: ~{touched / p50 / 1e9:.0f} GB/s device scan "
            f"({touched / 1e9:.1f}GB staged bytes / {p50 * 1e3:.1f}ms)")
    if bw:
        line += f" = {touched / p50 / bw * 100:.0f}% of nominal HBM bw"
    return line


# ---------------------------------------------------------------------------
# Flights (each runs in its own subprocess)
# ---------------------------------------------------------------------------

def flight_tpch(res: dict, big: bool) -> None:
    from tidb_tpu.bench.tpch import TPCH_Q1, TPCH_Q6, load_lineitem
    from tidb_tpu.session import Session

    _session_env()
    lines = res["lines"]
    sf = float(os.environ.get("BENCH_SF_BIG", 100)) if big else \
        float(os.environ.get("BENCH_SF", 10))
    repeat = int(os.environ.get("BENCH_REPEAT", 5))
    # 8 int64 + 3 int32 + 2 int8 columns adopted zero-copy by bulk_load
    # + remap/transient headroom
    n = _scale_to_ram(int(ROWS_PER_SF * sf), 115.0, f"tpch sf{sf:g}",
                      lines)
    sf_label = f"sf{sf:g}" if n == int(ROWS_PER_SF * sf) else \
        f"sf{n / ROWS_PER_SF:.0f}"
    log(f"tpch {sf_label}: generating {n} rows "
        f"(MemAvailable={_meminfo_gb('MemAvailable'):.0f}GB)")
    t0 = time.perf_counter()
    with _Heartbeat(f"tpch-{sf_label}-gen") as hb:
        arrays = generate_lineitem_chunked(n, hb)
    gen_s = time.perf_counter() - t0
    log(f"tpch {sf_label}: gen={gen_s:.0f}s; loading")
    session = Session()
    t0 = time.perf_counter()
    with _Heartbeat(f"tpch-{sf_label}-load") as hb:
        hb.rows = n
        load_lineitem(session, n, arrays=arrays)
    log(f"tpch {sf_label}: gen={gen_s:.0f}s "
        f"load={time.perf_counter() - t0:.0f}s ({n} rows)")
    if not big:
        res["values"]["py_baseline"] = interpreted_q6_baseline(arrays)
    got = session.query(TPCH_Q6)[0][0]
    log("q6 ran")
    assert got is not None and got.unscaled == q6_oracle(arrays), "q6"
    log("q6 digest OK")
    check_q1(session.query(TPCH_Q1), arrays)
    log("digests OK; timing")
    q6_ts = times(lambda: session.query(TPCH_Q6), repeat)
    note_attribution(res, f"q6_{sf_label}", session)
    q1_ts = times(lambda: session.query(TPCH_Q1), repeat)
    note_attribution(res, f"q1_{sf_label}", session)
    l6, q6_rps = report(f"q6_{sf_label}", q6_ts, n)
    l1, q1_rps = report(f"q1_{sf_label}", q1_ts, n)
    lines += [l6, l1]
    res["values"][f"q6_{'big' if big else 'small'}"] = q6_rps
    res["values"][f"q1_{'big' if big else 'small'}"] = q1_rps
    res["values"]["rows_" + ("big" if big else "small")] = n
    if big:
        # staged widths (client._narrow_stats): shipdate int16, discount
        # int8, quantity int16, extendedprice int32 (+1B valid lane each,
        # +1B shared visibility)
        lines.append(_hbm_line(f"q6_{sf_label}", q6_ts[len(q6_ts) // 2],
                               n, (2 + 1 + 2 + 4) + 4 + 1))
        # q1 staged widths: shipdate/quantity int16, extendedprice int32,
        # returnflag/linestatus/discount/tax int8 (+7 valid lanes +vis)
        lines.append(_hbm_line(f"q1_{sf_label}", q1_ts[len(q1_ts) // 2],
                               n, (2 + 2 + 4 + 4) + 7 + 1))
        return

    # concurrent throughput (separate, labeled)
    n_clients = int(os.environ.get("BENCH_CLIENTS", 8))

    def throughput(sql, per=2) -> float:
        import threading

        sessions = [Session(session.storage, cop=session.cop)
                    for _ in range(n_clients)]
        for s in sessions:
            s.query(sql)
        errs: list[BaseException] = []

        def run(s):
            try:
                for _ in range(per):
                    s.query(sql)
            except BaseException as e:
                errs.append(e)

        best = 0.0
        for _ in range(2):
            threads = [threading.Thread(target=run, args=(s,))
                       for s in sessions]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            if errs:
                raise errs[0]
            best = max(best, n_clients * per * n /
                       (time.perf_counter() - t0))
        return best

    tput = throughput(TPCH_Q6)
    res["values"]["q6_concurrent"] = tput
    lines.append(f"q6 concurrent throughput ({n_clients} clients): "
                 f"{tput / 1e6:.1f}M rows/s")

    # Q10 — the fused join+agg+topn shape (device multi-key TopN over a
    # snowflake join) tracked every round at a small join-corpus scale
    from tidb_tpu.bench.tpch_data import generate_tpch, load_table
    q10_sf = float(os.environ.get("BENCH_Q10_SF", 1))
    t0 = time.perf_counter()
    with _Heartbeat(f"tpch-q10-sf{q10_sf:g}-gen+load") as hb:
        jdata = generate_tpch(q10_sf, 17)
        jdata.pop("partsupp", None)  # unused by q7/q8/q10: free first
        hb.rows = len(jdata["lineitem"]["l_orderkey"])
        js = Session()
        for t in ("customer", "orders", "lineitem", "nation", "part",
                  "supplier", "region"):
            load_table(js, t, jdata[t])
    log(f"q10 corpus sf{q10_sf:g}: gen+load="
        f"{time.perf_counter() - t0:.0f}s")
    res["values"]["q10_small"] = time_q10(
        res, js, jdata, f"q10_sf{q10_sf:g}", repeat)
    # Q7/Q8 — the EXTRACT-year grouped aggregations newly
    # device-resident in round 14b (ISSUE 14), on the same join corpus
    res["values"]["q7_small"] = time_q7(
        res, js, jdata, f"q7_sf{q10_sf:g}", repeat)
    res["values"]["q8_small"] = time_q8(
        res, js, jdata, f"q8_sf{q10_sf:g}", repeat)


def flight_joins(res: dict) -> None:
    from tidb_tpu.bench.tpch_data import generate_tpch, load_table
    from tidb_tpu.bench.tpch_queries import TPCH_QUERIES
    from tidb_tpu.session import Session

    _session_env()
    lines = res["lines"]
    join_sf = float(os.environ.get("BENCH_JOIN_SF", 10))
    repeat = int(os.environ.get("BENCH_REPEAT", 5))
    t0 = time.perf_counter()
    with _Heartbeat(f"tpch-join-sf{join_sf:g}-gen+load") as hb:
        jdata = generate_tpch(join_sf, 11)
        hb.rows = len(jdata["lineitem"]["l_orderkey"])
        js = Session()
        for t in jdata:
            load_table(js, t, jdata[t])
    jrows = len(jdata["lineitem"]["l_orderkey"])
    log(f"tpch join corpus sf{join_sf:g}: gen+load="
        f"{time.perf_counter() - t0:.0f}s ({jrows} lineitem rows)")
    want3 = q3_oracle(jdata)
    got3 = [(int(r[0]), r[1].unscaled) for r in js.query(
        TPCH_QUERIES["q3"])]
    assert got3 == want3, f"q3 digest: {got3[:3]} vs {want3[:3]}"
    want5 = q5_oracle(jdata)
    got5 = {r[0]: r[1].unscaled for r in js.query(TPCH_QUERIES["q5"])}
    nnames, _ = jdata["nation"]["n_name"]
    nat_by_name = {nm: int(k) for nm, k in zip(
        nnames, jdata["nation"]["n_nationkey"])}
    got5 = {nat_by_name[name]: v for name, v in got5.items()}
    assert got5 == want5, f"q5 digest: {got5} vs {want5}"
    log("join digests OK; timing q3/q5")
    q3_ts = times(lambda: js.query(TPCH_QUERIES["q3"]), repeat)
    note_attribution(res, f"q3_sf{join_sf:g}", js)
    q5_ts = times(lambda: js.query(TPCH_QUERIES["q5"]), repeat)
    note_attribution(res, f"q5_sf{join_sf:g}", js)
    l3, q3_rps = report(f"q3_sf{join_sf:g}", q3_ts, jrows)
    l5, q5_rps = report(f"q5_sf{join_sf:g}", q5_ts, jrows)
    lines += [l3, l5]
    res["values"]["q3"] = q3_rps
    res["values"]["q5"] = q5_rps


def flight_ssb(res: dict) -> None:
    from tidb_tpu.bench import ssb
    from tidb_tpu.session import Session

    _session_env()
    lines = res["lines"]
    ssb_sf = float(os.environ.get("BENCH_SSB_SF", 100))
    repeat = int(os.environ.get("BENCH_REPEAT", 5))
    # 14 distinct int64 column buffers (commitdate shares orderdate's)
    # adopted zero-copy + 2 int8 code arrays + int32 dict columns +
    # generator transients
    n = _scale_to_ram(int(ssb.ROWS_PER_SF * ssb_sf), 155.0, "ssb", lines)
    sf = n / ssb.ROWS_PER_SF
    t0 = time.perf_counter()
    with _Heartbeat(f"ssb-sf{sf:g}-gen+load") as hb:
        lo = ssb.generate_lineorder(sf)
        hb.rows = len(lo["lo_orderdate"]) if "lo_orderdate" in lo else 0
        ss = Session()
        nrows_ssb = ssb.load_ssb(ss, sf, lineorder=lo)
        hb.rows = nrows_ssb
    log(f"ssb sf{sf:g}: gen+load={time.perf_counter() - t0:.0f}s "
        f"({nrows_ssb} lineorder rows)")
    for q in ("q1.1", "q1.2", "q1.3"):
        got = ss.query(ssb.SSB_QUERIES[q])[0][0]
        assert got is not None and int(got) == ssb.q1_oracle(lo, q), q
        ts = times(lambda sql=ssb.SSB_QUERIES[q]: ss.query(sql), repeat)
        note_attribution(res, f"ssb_{q}_sf{sf:g}", ss)
        line, rps = report(f"ssb_{q}_sf{sf:g}", ts, nrows_ssb)
        lines.append(line)
        res["values"][f"ssb_{q}"] = rps


def flight_cb(res: dict) -> None:
    from tidb_tpu.bench import clickbench as cbench
    from tidb_tpu.session import Session

    _session_env()
    lines = res["lines"]
    cb_rows = int(float(os.environ.get("BENCH_CB_ROWS", 1e8)))
    repeat = int(os.environ.get("BENCH_REPEAT", 5))
    cb_rows = _scale_to_ram(cb_rows, 110.0, "clickbench", lines)
    t0 = time.perf_counter()
    with _Heartbeat("clickbench-gen+load") as hb:
        hits = cbench.generate_hits(cb_rows)
        hb.rows = cb_rows
        cs = Session()
        cbench.load_hits(cs, cb_rows, hits=hits)
    log(f"clickbench hits_{cb_rows // 1_000_000}m: gen+load="
        f"{time.perf_counter() - t0:.0f}s")
    for q, sql in cbench.CB_QUERIES.items():
        got = cs.query(sql)
        want = cbench.cb_oracle(hits, q)
        if q in ("cb_scan", "cb_sum"):
            ok = int(got[0][0]) == want
        elif q == "cb_agg":
            ok = (int(got[0][0]), int(got[0][1])) == want
        else:
            ok = [(int(a), int(b)) for a, b in got] == want
        assert ok, f"{q} digest"
        ts = times(lambda s2=sql: cs.query(s2), repeat)
        note_attribution(res, q, cs)
        line, rps = report(q, ts, cb_rows)
        lines.append(line)
        res["values"][q] = rps


def flight_multichip(res: dict) -> None:
    """Mesh data plane: Q1/Q6-class scan+agg over epochs sharded across
    the device mesh vs the single-device path — per-query rows/s for
    both, plus per-device placement (shard spec + bytes per device from
    `arr.sharding` / `addressable_shards`). Forces an 8-virtual-device
    CPU mesh when no real multi-chip backend was requested
    (BENCH_PLATFORM unset), mirroring the MULTICHIP board's dryrun."""
    import jax

    want = int(os.environ.get("BENCH_MESH_DEVICES", 8))
    if not os.environ.get("BENCH_PLATFORM"):
        # prefer REAL multi-device hardware: probe the default backend
        # in a throwaway child (this process must not initialize a
        # backend before deciding — init is one-shot), and only fall
        # back to `want` virtual CPU devices when the default backend
        # is cpu or single-device
        ndev, backend = 1, "cpu"
        try:
            probe = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(jax.default_backend(), "
                 "len(jax.devices()))"],
                capture_output=True, text=True, timeout=180)
            parts = probe.stdout.split()
            if len(parts) >= 2:
                backend, ndev = parts[-2], int(parts[-1])
        except (subprocess.TimeoutExpired, OSError, ValueError):
            pass
        if backend != "cpu" and ndev > 1:
            log(f"multichip: using default backend {backend} "
                f"({ndev} devices)")
        else:
            try:  # must precede backend init; ignored afterwards
                jax.config.update("jax_platforms", "cpu")
                jax.config.update("jax_num_cpu_devices", want)
            except AttributeError:
                os.environ["XLA_FLAGS"] = (
                    os.environ.get("XLA_FLAGS", "")
                    + f" --xla_force_host_platform_device_count={want}")
    _session_env()
    from tidb_tpu.bench.tpch import TPCH_Q1, TPCH_Q6, load_lineitem
    from tidb_tpu.copr import mesh as M
    from tidb_tpu.copr.client import CopClient
    from tidb_tpu.session import Session

    lines = res["lines"]
    n_dev = len(jax.devices())
    repeat = int(os.environ.get("BENCH_REPEAT", 5))
    n = _scale_to_ram(int(float(os.environ.get("BENCH_MESH_ROWS", 4e6))),
                      115.0, "multichip", lines)
    log(f"multichip: {n_dev} devices, {n} rows")
    with _Heartbeat("multichip-gen") as hb:
        arrays = generate_lineitem_chunked(n, hb)
    single = Session(cop=CopClient())
    with _Heartbeat("multichip-load") as hb:
        hb.rows = n
        load_lineitem(single, n, arrays=arrays)
    plane = M.MeshPlane(M.MeshConfig(
        enabled=True, shard_threshold_rows=min(1 << 20, max(n // 2, 1))))
    mesh = Session(single.storage, cop=plane.client_for(single.storage))
    res["values"]["mesh_devices"] = n_dev
    lines.append(f"multichip: {n_dev} devices "
                 f"(active={plane.active}), {n} rows")

    want6 = q6_oracle(arrays)
    got = mesh.query(TPCH_Q6)[0][0]
    assert got is not None and got.unscaled == want6, "mesh q6 digest"
    assert single.query(TPCH_Q6)[0][0].unscaled == want6
    check_q1(mesh.query(TPCH_Q1), arrays)
    log("multichip digests OK (mesh == single == oracle); timing")

    # flight-recorder snapshot rides the result: per-query skew +
    # per-operator max-shard share, per-device bytes, exchange totals —
    # MULTICHIP_r06+ records placement QUALITY, not just rows/s
    from tidb_tpu import obs as _obs
    mesh_info: dict = {"devices": n_dev, "queries": {}}
    for name, sql in (("q6", TPCH_Q6), ("q1", TPCH_Q1)):
        ts_s = times(lambda s=sql: single.query(s), repeat)
        ts_m = times(lambda s=sql: mesh.query(s), repeat)
        note_attribution(res, f"multichip_{name}_mesh", mesh)
        _, rps_s = report(f"{name}_single", ts_s, n)
        _, rps_m = report(f"{name}_mesh", ts_m, n)
        res["values"][f"{name}_single_1dev"] = rps_s
        res["values"][f"{name}_mesh_{n_dev}dev"] = rps_m
        om = mesh.last_op_mesh
        skew = max((v[1] for v in om.values()), default=0.0)
        mesh_info["queries"][name] = {
            "skew": round(skew, 3),
            "op_shares": {k: round(v[0], 4) for k, v in om.items()},
        }
        lines.append(
            f"multichip {name}: single-device "
            f"{rps_s / 1e6:.1f}M rows/s vs {n_dev}-device mesh "
            f"{rps_m / 1e6:.1f}M rows/s ({rps_m / rps_s:.2f}x), "
            f"skew={skew:.2f}")

    rep = M.placement_report(mesh.cop)
    lines.append(
        f"multichip placement: {rep['sharded_arrays']} sharded + "
        f"{rep['replicated_arrays']} replicated arrays, "
        f"spec={rep['shard_spec']}")
    for dev in sorted(rep["device_bytes"]):
        lines.append(f"multichip placement {dev}: "
                     f"{rep['device_bytes'][dev]} bytes")
    res["values"]["mesh_device_bytes"] = rep["device_bytes"]
    res["values"]["mesh_sharded_arrays"] = rep["sharded_arrays"]
    mesh_info["device_bytes"] = rep["device_bytes"]
    mesh_info["device_peak_bytes"] = plane.device_peak_bytes()
    mesh_info["reshard_bytes_total"] = _obs.MESH_RESHARD_BYTES.get()
    res["mesh"] = mesh_info
    lines.append(
        f"multichip exchange: "
        f"{int(mesh_info['reshard_bytes_total'])} reshard bytes total")

    # Q10 over the mesh: the fused join+agg+topn shape executing
    # partition-wise (sharded probe, candidate blocks per device) vs the
    # single-device path — both digest-checked against the oracle. Runs
    # AFTER the placement report above: its corpus REPLACES the flight's
    # lineitem table (load_table drops + recreates), and the placement/
    # device-bytes record must keep describing the main workload.
    from tidb_tpu.bench.tpch_data import generate_tpch, load_table
    q10_sf = max(0.1, min(float(os.environ.get(
        "BENCH_MESH_Q10_SF", n / ROWS_PER_SF)), 10.0))
    with _Heartbeat(f"multichip-q10-sf{q10_sf:g}-gen+load") as hb:
        jdata = generate_tpch(q10_sf, 17)
        jdata.pop("partsupp", None)  # unused by q7/q8/q10: free first
        hb.rows = len(jdata["lineitem"]["l_orderkey"])
        for t in ("customer", "orders", "lineitem", "nation", "part",
                  "supplier", "region"):
            load_table(single, t, jdata[t])
    jrows = len(jdata["lineitem"]["l_orderkey"])
    rps_s10 = time_q10(res, single, jdata, "multichip_q10_single", repeat)
    rps_m10 = time_q10(res, mesh, jdata, "multichip_q10_mesh", repeat)
    res["values"]["q10_single_1dev"] = rps_s10
    res["values"][f"q10_mesh_{n_dev}dev"] = rps_m10
    om = mesh.last_op_mesh
    mesh_info["queries"]["q10"] = {
        "skew": round(max((v[1] for v in om.values()), default=0.0), 3),
        "op_shares": {k: round(v[0], 4) for k, v in om.items()},
    }
    lines.append(
        f"multichip q10 ({jrows} lineitem rows): single-device "
        f"{rps_s10 / 1e6:.1f}M rows/s vs {n_dev}-device mesh "
        f"{rps_m10 / 1e6:.1f}M rows/s ({rps_m10 / max(rps_s10, 1):.2f}x)")
    # Q7/Q8 — the round-14b grouped-aggregation conversions, sharded vs
    # single on the same corpus (ISSUE 14's missing number)
    for qname, timer in (("q7", time_q7), ("q8", time_q8)):
        rps_s = timer(res, single, jdata,
                      f"multichip_{qname}_single", repeat)
        rps_m = timer(res, mesh, jdata,
                      f"multichip_{qname}_mesh", repeat)
        res["values"][f"{qname}_single_1dev"] = rps_s
        res["values"][f"{qname}_mesh_{n_dev}dev"] = rps_m
        om = mesh.last_op_mesh
        mesh_info["queries"][qname] = {
            "skew": round(max((v[1] for v in om.values()),
                              default=0.0), 3),
            "op_shares": {k: round(v[0], 4) for k, v in om.items()},
        }
        lines.append(
            f"multichip {qname} ({jrows} lineitem rows): single-device "
            f"{rps_s / 1e6:.1f}M rows/s vs {n_dev}-device mesh "
            f"{rps_m / 1e6:.1f}M rows/s ({rps_m / max(rps_s, 1):.2f}x)")
    # dispatch ring taken LAST so the q10 dispatches are in the record
    mesh_info["dispatches"] = mesh.cop.recorder.snapshot()["dispatches"]


def flight_replica_read(res: dict) -> None:
    """Follower read tier: read QPS against ONE leader vs the same
    leader with serving follower REPLICA PROCESSES (real processes, so
    the offloaded compute actually leaves the router's CPU), p50/p99
    per mode and the routed fraction. The scaling claim of ROADMAP
    item 2 — read throughput grows with node count — gets a recorded
    number."""
    import shutil
    import signal as _signal

    _session_env()
    from tidb_tpu.session import Session
    from tidb_tpu.store.storage import Storage

    lines = res["lines"]
    n = int(float(os.environ.get("BENCH_REPLICA_ROWS", 1e5)))
    n_followers = int(os.environ.get("BENCH_REPLICA_FOLLOWERS", 2))
    workers = int(os.environ.get("BENCH_REPLICA_WORKERS", 8))
    seconds = float(os.environ.get("BENCH_REPLICA_SECONDS", 8))
    tmp = tempfile.mkdtemp(prefix="bench-replica-")
    procs: list[subprocess.Popen] = []
    leader = None
    try:
        leader = Storage(os.path.join(tmp, "leader"), shared=True,
                         rpc_listen="127.0.0.1:0")
        sess = Session(leader)
        sess.execute("create table rr (id bigint primary key, "
                     "grp bigint, v bigint)")
        rng = np.random.default_rng(7)
        vals = rng.integers(0, 1000, size=n)
        with _Heartbeat("replica-load") as hb:
            batch = 2000
            for lo in range(0, n, batch):
                hi = min(lo + batch, n)
                rows = ",".join(
                    f"({i},{i % 97},{int(vals[i])})"
                    for i in range(lo, hi))
                sess.execute(f"insert into rr values {rows}")
                hb.rows = hi
        addr = f"127.0.0.1:{leader.rpc_server.port}"
        code = (
            "import sys\n"
            "from tidb_tpu.store.storage import Storage\n"
            "import time\n"
            "s = Storage(sys.argv[1], remote=sys.argv[2])\n"
            "print('follower ready', flush=True)\n"
            "time.sleep(1e9)\n")
        env = dict(os.environ, TIDB_TPU_REPLICA_APPLY_MS="100")
        env.setdefault("JAX_PLATFORMS", "cpu")
        for i in range(n_followers):
            procs.append(subprocess.Popen(
                [sys.executable, "-c", code,
                 os.path.join(tmp, f"f{i}"), addr],
                stdout=sys.stderr, stderr=sys.stderr, env=env,
                cwd=os.path.dirname(os.path.abspath(__file__))))
        deadline = time.monotonic() + 180.0
        while time.monotonic() < deadline:
            serving = [m for m in leader.rpc_server.members()
                       if m["role"] == "follower" and m.get("serving")]
            if len(serving) >= n_followers:
                break
            time.sleep(0.25)
        else:
            raise RuntimeError(
                f"followers never started serving: "
                f"{leader.rpc_server.members()}")
        log(f"replica_read: {n_followers} serving followers up, "
            f"{n} rows, {workers} workers x {seconds:.0f}s per mode")

        queries = [f"select sum(v), count(*) from rr where grp = {g}"
                   for g in range(97)]

        def run_mode(mode: str) -> dict:
            lat: list[list[float]] = [[] for _ in range(workers)]
            stop = threading.Event()

            def work(wi: int) -> None:
                s = Session(leader)
                s.execute(f"set tidb_replica_read = '{mode}'")
                k = wi
                while not stop.is_set():
                    t0 = time.perf_counter()
                    s.query(queries[k % len(queries)])
                    lat[wi].append(time.perf_counter() - t0)
                    k += 1

            # warm both paths (compile) before the timed window; the
            # routed-fraction baseline snapshots AFTER the warm query
            warm = Session(leader)
            warm.execute(f"set tidb_replica_read = '{mode}'")
            warm.query(queries[0])
            served0 = leader.obs.replica_reads.get(outcome="served")
            threads = [threading.Thread(target=work, args=(i,),
                                        daemon=True)
                       for i in range(workers)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            time.sleep(seconds)
            stop.set()
            for t in threads:
                t.join(timeout=30.0)
            wall = time.perf_counter() - t0
            alls = sorted(x for ws in lat for x in ws)
            total = len(alls)
            served = leader.obs.replica_reads.get(
                outcome="served") - served0
            return {
                "qps": total / wall,
                "p50_ms": alls[total // 2] * 1e3 if alls else 0.0,
                "p99_ms": alls[min(total - 1, int(total * 0.99))] * 1e3
                if alls else 0.0,
                "routed_fraction": served / total if total else 0.0,
            }

        base = run_mode("leader")
        routed = run_mode("follower")
        res["values"]["replica_read_qps_leader"] = round(base["qps"], 1)
        res["values"]["replica_read_qps_routed"] = \
            round(routed["qps"], 1)
        res["values"]["replica_read_routed_fraction"] = \
            round(routed["routed_fraction"], 3)
        res["values"]["replica_read_followers"] = n_followers
        for mode, r in (("leader-only", base),
                        (f"leader+{n_followers}f", routed)):
            lines.append(
                f"replica_read {mode}: {r['qps']:.0f} QPS "
                f"p50={r['p50_ms']:.1f}ms p99={r['p99_ms']:.1f}ms "
                f"routed={r['routed_fraction']:.0%}")
        lines.append(
            f"replica_read scaling: {routed['qps'] / max(base['qps'], 1e-9):.2f}x "
            f"QPS with {n_followers} serving followers "
            f"({workers} workers, {n} rows)")

        # ranged phase: the same routed read with the range plane
        # armed as a 4-range leader fleet and the range-aware covering
        # gate on — every SELECT must be covered by the min published
        # closed_ts over the ranges its span touches, so the board
        # carries the gate's real cost: QPS under the gate plus the
        # fraction of worker busy-time spent in the covered_ts wait
        from tidb_tpu import obs as _obs
        from tidb_tpu.kv import tablecodec as _tc
        tid = leader.catalog.table("test", "rr").id
        splits = [_tc.record_key(int(tid), h)
                  for h in (n // 4, n // 2, 3 * n // 4)]
        leader.arm_ranges(enabled=True, split_points=splits,
                          lease_ms=150)
        leader.replica_read.range_aware = True
        nr = len(leader.ranges.server.specs)
        log(f"replica_read: range plane armed ({nr} ranges), "
            "range-aware covering gate on")
        wait0 = _obs.WAIT_SECONDS_TOTAL.get(state="covered_ts")
        ranged = run_mode("follower")
        waited = _obs.WAIT_SECONDS_TOTAL.get(
            state="covered_ts") - wait0
        busy = workers * seconds
        res["values"]["replica_read_qps_ranged"] = \
            round(ranged["qps"], 1)
        res["values"]["replica_read_covered_wait_fraction"] = \
            round(waited / busy, 4)
        res["values"]["replica_read_ranges"] = nr
        lines.append(
            f"replica_read ranged ({nr} ranges, gate on): "
            f"{ranged['qps']:.0f} QPS p50={ranged['p50_ms']:.1f}ms "
            f"p99={ranged['p99_ms']:.1f}ms "
            f"routed={ranged['routed_fraction']:.0%} "
            f"covered-ts wait {waited / busy:.1%} of busy time")
        lines.append(
            f"replica_read gate cost: "
            f"{ranged['qps'] / max(routed['qps'], 1e-9):.2f}x QPS vs "
            f"ungated routed read (fresh read_ts waits for the next "
            f"closed-ts heartbeat)")
    finally:
        for p in procs:
            try:
                p.send_signal(_signal.SIGTERM)
            except OSError:
                pass
        for p in procs:
            try:
                p.wait(timeout=20)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait(timeout=10)
        if leader is not None:
            leader.close()
        shutil.rmtree(tmp, ignore_errors=True)


def _mini_client_module():
    """tests/mysql_client.py loaded by path (the wire flights reuse the
    independent protocol encoding the server tests are pinned by)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_mysql_client",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "tests", "mysql_client.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def flight_htap_mixed(res: dict) -> None:
    """The HTAP promise, measured: concurrent wire-path point
    get/update streams against Q1/Q6 analytical scans on ONE durable
    (sync-log=commit) server — the first recorded mixed workload.

    Board numbers: point p50/p99 (alone and under scan pressure),
    durable write QPS at 1/8/32 writers (cross-commit group fsync —
    amortization read from tidb_group_commit_batch_size), concurrent
    Q1/Q6 rows/s, and Top SQL attribution across the whole mix. The
    point ops run over the WIRE and must take the fast-path bypass
    (asserted via the `point` engine tag before anything is timed)."""
    import shutil

    _session_env()
    from tidb_tpu.bench.tpch import TPCH_Q1, TPCH_Q6, load_lineitem
    from tidb_tpu.server.server import Server
    from tidb_tpu.session import Session
    from tidb_tpu.store.storage import Storage

    mc = _mini_client_module()
    lines = res["lines"]
    point_rows = int(float(os.environ.get("BENCH_HTAP_POINT_ROWS", 1e5)))
    scan_rows = _scale_to_ram(
        int(float(os.environ.get("BENCH_HTAP_SCAN_ROWS",
                                 2_000_000))), 115.0, "htap scan", lines)
    seconds = float(os.environ.get("BENCH_HTAP_SECONDS", 6))
    readers = int(os.environ.get("BENCH_HTAP_READERS", 4))
    tmp = tempfile.mkdtemp(prefix="bench-htap-")
    server = None
    storage = None
    try:
        storage = Storage(os.path.join(tmp, "db"), sync_log="commit")
        storage.obs.topsql.configure(enabled=True, window_s=600)
        sess = Session(storage)
        sess.execute("create table sbtest (id bigint primary key, "
                     "k bigint, c varchar(64))")
        with _Heartbeat("htap-point-load") as hb:
            for lo in range(0, point_rows, 2000):
                hi = min(lo + 2000, point_rows)
                sess.execute("insert into sbtest values " + ",".join(
                    f"({i},{i % 1000},'c{i:020d}')"
                    for i in range(lo, hi)))
                hb.rows = hi
        with _Heartbeat("htap-lineitem-gen") as hb:
            arrays = generate_lineitem_chunked(scan_rows, hb)
        with _Heartbeat("htap-lineitem-load") as hb:
            hb.rows = scan_rows
            load_lineitem(sess, scan_rows, arrays=arrays)
        server = Server(storage, port=0, max_connections=256)
        server.start()
        addr = ("127.0.0.1", server.port)

        # the bypass gate BEFORE timing anything: wire-path point ops
        # must show the `point` engine (EXPLAIN ANALYZE surfaces it)
        probe = mc.MiniClient(*addr)
        ea = probe.query(
            "explain analyze select id, k from sbtest where id = 5")
        assert ea and ea[0][3] == "point", f"point bypass lost: {ea}"
        lines.append(f"htap point path: {ea[0][0]} engine={ea[0][3]} "
                     f"[{ea[0][4]}]")
        probe.close()

        def run_phase(n_read: int, n_write: int, n_scan: int,
                      secs: float) -> dict:
            stop = threading.Event()
            read_lat: list[list[float]] = [[] for _ in range(n_read)]
            write_lat: list[list[float]] = [[] for _ in range(n_write)]
            scan_counts = {"q1": [], "q6": []}
            errs: list[BaseException] = []

            def points(wi: int, lat: list, write: bool) -> None:
                try:
                    cl = mc.MiniClient(*addr)
                    rng = np.random.default_rng(1000 * wi + int(write))
                    ids = rng.integers(0, point_rows, size=1 << 14)
                    j = 0
                    while not stop.is_set():
                        i = int(ids[j & 0x3FFF])
                        j += 1
                        t0 = time.perf_counter()
                        if write:
                            cl.execute("update sbtest set k = k + 1 "
                                       f"where id = {i}")
                        else:
                            cl.query("select id, k, c from sbtest "
                                     f"where id = {i}")
                        lat.append(time.perf_counter() - t0)
                    cl.close()
                except BaseException as e:  # noqa: BLE001
                    errs.append(e)

            def scans() -> None:
                try:
                    cl = mc.MiniClient(*addr)
                    while not stop.is_set():
                        for name, sql in (("q6", TPCH_Q6),
                                          ("q1", TPCH_Q1)):
                            t0 = time.perf_counter()
                            cl.query(sql)
                            scan_counts[name].append(
                                time.perf_counter() - t0)
                            if stop.is_set():
                                break
                    cl.close()
                except BaseException as e:  # noqa: BLE001
                    errs.append(e)

            threads = (
                [threading.Thread(target=points, args=(i, read_lat[i],
                                                       False))
                 for i in range(n_read)]
                + [threading.Thread(target=points, args=(i, write_lat[i],
                                                         True))
                   for i in range(n_write)]
                + [threading.Thread(target=scans)
                   for _ in range(n_scan)])
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            time.sleep(secs)
            stop.set()
            for t in threads:
                t.join(timeout=60.0)
            wall = time.perf_counter() - t0
            if errs:
                raise errs[0]
            reads = sorted(x for ws in read_lat for x in ws)
            writes = sorted(x for ws in write_lat for x in ws)

            def pct(v: list, q: float) -> float:
                return v[min(len(v) - 1, int(len(v) * q))] * 1e3 \
                    if v else 0.0

            return {
                "wall": wall,
                "read_qps": len(reads) / wall,
                "write_qps": len(writes) / wall,
                "read_p50": pct(reads, 0.5), "read_p99": pct(reads, 0.99),
                "write_p50": pct(writes, 0.5),
                "write_p99": pct(writes, 0.99),
                "scans": {k: list(v) for k, v in scan_counts.items()},
            }

        # ---- durable write QPS by concurrency (group-fsync scaling) ----
        hist = storage.obs.group_commit_batch
        for conc in (1, 8, 32):
            _, sum0, n0 = hist.snapshot()
            ph = run_phase(0, conc, 0, seconds)
            _, sum1, n1 = hist.snapshot()
            batches = n1 - n0
            avg_batch = (sum1 - sum0) / batches if batches else 1.0
            res["values"][f"htap_write_qps_{conc}"] = \
                round(ph["write_qps"], 1)
            res["values"][f"htap_group_batch_{conc}"] = \
                round(avg_batch, 2)
            lines.append(
                f"htap_mixed write x{conc}: {ph['write_qps']:.0f} "
                f"durable QPS p50={ph['write_p50']:.2f}ms "
                f"p99={ph['write_p99']:.2f}ms "
                f"(group fsync avg batch {avg_batch:.1f} over "
                f"{batches} fsyncs)")
        q1 = res["values"].get("htap_write_qps_1", 0) or 1
        res["values"]["htap_write_scaling_32x"] = round(
            res["values"].get("htap_write_qps_32", 0) / q1, 2)
        lines.append(
            f"htap_mixed write scaling: "
            f"{res['values']['htap_write_scaling_32x']:.1f}x QPS at 32 "
            "writers vs 1 under sync-log=commit")

        # ---- wait-profile zero-overhead check: the x8 write phase
        # again with performance.wait-profile-enabled on (per-statement
        # typed ledger + windowed digest attribution) ----
        storage.obs.waitprofile.configure(enabled=True)
        try:
            wp_ph = run_phase(0, 8, 0, seconds)
        finally:
            storage.obs.waitprofile.configure(enabled=False)
        base = res["values"].get("htap_write_qps_8", 0) or 1
        res["values"]["htap_write_qps_8_wp"] = round(wp_ph["write_qps"], 1)
        res["values"]["htap_wp_ratio"] = round(
            wp_ph["write_qps"] / base, 3)
        lines.append(
            f"htap_mixed write x8 +wait-profile: "
            f"{wp_ph['write_qps']:.0f} durable QPS "
            f"({res['values']['htap_wp_ratio']:.3f}x of ledger-off)")
        wrows = storage.obs.waitprofile.table_rows()
        upd = [r for r in wrows if "update" in (r[2] or "")][:3]
        for r in upd:
            lines.append(
                f"htap_mixed waitprofile: {r[6]} {r[7]:.1f}ms "
                f"({r[8]:.0%} of wall) — {r[2][:60]}")

        # ---- point reads alone (baseline), then the full HTAP mix ----
        warm = mc.MiniClient(*addr)
        warm.query(TPCH_Q6)
        warm.query(TPCH_Q1)  # compile outside the timed window
        warm.close()
        alone = run_phase(readers, 0, 0, seconds)
        mixed = run_phase(readers, 8, 1, max(seconds, 8.0))
        res["values"]["htap_point_qps"] = round(mixed["read_qps"], 1)
        res["values"]["htap_point_p50_ms"] = round(mixed["read_p50"], 3)
        res["values"]["htap_point_p99_ms"] = round(mixed["read_p99"], 3)
        res["values"]["htap_point_alone_p99_ms"] = \
            round(alone["read_p99"], 3)
        lines.append(
            f"htap_mixed point alone x{readers}: "
            f"{alone['read_qps']:.0f} QPS p50={alone['read_p50']:.2f}ms "
            f"p99={alone['read_p99']:.2f}ms")
        for name in ("q6", "q1"):
            ts = mixed["scans"][name]
            if ts:
                p50 = sorted(ts)[len(ts) // 2]
                rps = scan_rows / p50
                res["values"][f"htap_scan_{name}_rows_s"] = round(rps)
                lines.append(
                    f"htap_mixed {name} under mix: {rps / 1e6:.1f}M "
                    f"rows/s ({len(ts)} scans, p50={p50 * 1e3:.0f}ms)")
        lines.append(
            f"htap_mixed point under mix x{readers} (+8 writers, "
            f"+Q1/Q6 stream): {mixed['read_qps']:.0f} QPS "
            f"p50={mixed['read_p50']:.2f}ms p99={mixed['read_p99']:.2f}ms")

        # ---- Top SQL attribution for the whole mix ----
        digests: dict[str, dict] = {}
        for b in storage.obs.topsql.snapshot():
            ents = list(b["digests"].values())
            if b["other"] is not None:
                ents.append(b["other"])
            for e in ents:
                d = digests.setdefault(e["digest"], {
                    "text": e["digest_text"], "execs": 0, "wall_ms": 0.0})
                d["execs"] += e["exec_count"]
                d["wall_ms"] += e["sum_wall_s"] * 1e3
        top = sorted(digests.values(), key=lambda d: -d["wall_ms"])[:5]
        for d in top:
            lines.append(
                f"htap_mixed topsql: {d['wall_ms']:.0f}ms over "
                f"{d['execs']} execs — {d['text'][:72]}")
        res["topsql"] = top
    finally:
        if server is not None:
            server.close()
        if storage is not None:
            storage.close()
        shutil.rmtree(tmp, ignore_errors=True)


def flight_range_write(res: dict) -> None:
    """Range-sharded write leadership: DURABLE (sync-log=commit,
    percolator 2PC through the range RPC tier) write QPS against ONE
    range leader vs N — the write-scaling claim of the range plane.
    With one range every commit serializes behind one WAL stream; with
    N ranges the same workload fans out over N independently-fsynced
    engines, so durable QPS should grow until the disk saturates."""
    import shutil

    _session_env()
    from tidb_tpu.kv.mvcc import OP_PUT, Mutation
    from tidb_tpu.kv.rangeclient import RangeRouter
    from tidb_tpu.kv.rangemeta import split_keyspace
    from tidb_tpu.kv.tso import TimestampOracle
    from tidb_tpu.kv.twopc import TwoPhaseCommitter
    from tidb_tpu.rpc.ranged import RangeServer

    from tidb_tpu import obs as _obs

    lines = res["lines"]
    n_leaders = int(os.environ.get("BENCH_RANGE_LEADERS", 4))
    workers = int(os.environ.get("BENCH_RANGE_WORKERS", 8))
    seconds = float(os.environ.get("BENCH_RANGE_SECONDS", 6))
    # third phase: the wait-profile zero-overhead check — the same
    # n_leaders workload with a fresh per-txn WaitLedger installed
    # (what performance.wait-profile-enabled costs this path)
    from tidb_tpu.obs_heat import RangeHeatRecorder

    qps: dict[tuple[int, bool], float] = {}
    heat_board: dict = {}
    for count, with_ledger in ((1, False), (n_leaders, False),
                               (n_leaders, True)):
        tmp = tempfile.mkdtemp(prefix=f"bench-range-{count}-")
        srv = None
        routers: list = []
        # the n-leader phase runs with the keyspace heat plane armed:
        # the flight result carries the observed per-range traffic
        # split (the keyspace-balance trail of the scaling claim)
        heat = None
        if count == n_leaders and not with_ledger:
            heat = RangeHeatRecorder()
            heat.configure(enabled=True, bucket_seconds=1,
                           sustained_buckets=1)
            heat.set_specs(split_keyspace(count))
        try:
            srv = RangeServer(tmp, lease_ms=60_000,
                              specs=split_keyspace(count),
                              sync_log="commit", heat=heat)
            tso = TimestampOracle()
            stop = threading.Event()
            counts = [0] * workers
            # uniform single-key txns spread across the keyspace: the
            # SAME workload both phases, only the range count changes
            def worker(w: int) -> None:
                router = RangeRouter(root=tmp)
                routers.append(router)
                committer = TwoPhaseCommitter(router, tso,
                                              lock_ttl=3000)
                i = 0
                while not stop.is_set():
                    if with_ledger:
                        # per-statement semantics: a fresh ledger per
                        # txn, like Session._execute_observed installs
                        _obs.install_wait_ledger(_obs.WaitLedger())
                    key = bytes([(w * 37 + i * 11) % 256]) + \
                        b"k%d.%d" % (w, i)
                    committer.commit(
                        [Mutation(OP_PUT, key, b"v%d" % i)], tso.ts())
                    counts[w] += 1
                    i += 1
                if with_ledger:
                    _obs.install_wait_ledger(None)
            threads = [threading.Thread(target=worker, args=(w,),
                                        name=f"bench-range-w{w}",
                                        daemon=True)
                       for w in range(workers)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            time.sleep(seconds)
            stop.set()
            for t in threads:
                t.join(timeout=30)
            wall = time.perf_counter() - t0
            qps[(count, with_ledger)] = sum(counts) / wall
            tag = " +wait-profile" if with_ledger else ""
            lines.append(
                f"range_write x{count} leader{'s' if count > 1 else ''}"
                f"{tag}: {qps[(count, with_ledger)]:.0f} durable txn/s "
                f"({workers} workers, sync-log=commit, "
                f"{sum(counts)} commits / {wall:.1f}s)")
            if heat is not None:
                payload = heat.debug_payload()
                heat_board = {
                    "ranges": payload.get("totals", {}),
                    "findings": payload.get("findings", []),
                    "heatmap": payload.get("heatmap", []),
                }
                writes = {rid: t[2] for rid, t
                          in heat_board["ranges"].items()}
                total_w = sum(writes.values()) or 1
                split = ", ".join(
                    f"r{rid}={w * 100.0 / total_w:.0f}%"
                    for rid, w in sorted(writes.items()))
                lines.append(f"range_write heat split: {split}")
                for hl in heat_board["heatmap"]:
                    lines.append(f"  {hl}")
                for f in heat_board["findings"]:
                    lines.append(
                        f"range_write heat finding: {f['rule']} "
                        f"{f['item']} {f['value']}")
        finally:
            for router in routers:
                router.close()
            if srv is not None:
                srv.close()
            shutil.rmtree(tmp, ignore_errors=True)
    res["values"]["range_write_qps_1"] = round(qps[(1, False)], 1)
    res["values"][f"range_write_qps_{n_leaders}"] = \
        round(qps[(n_leaders, False)], 1)
    res["values"]["range_write_scaling"] = round(
        qps[(n_leaders, False)] / max(qps[(1, False)], 1e-9), 2)
    res["values"]["range_write_leaders"] = n_leaders
    lines.append(
        f"range_write scaling: "
        f"{res['values']['range_write_scaling']:.2f}x durable write "
        f"QPS at {n_leaders} range leaders vs 1")
    res["heatmap"] = heat_board
    res["values"]["range_write_qps_wp"] = round(qps[(n_leaders, True)], 1)
    res["values"]["range_write_wp_ratio"] = round(
        qps[(n_leaders, True)] / max(qps[(n_leaders, False)], 1e-9), 3)

    # fourth phase: the acting loop under load — a skewed hot band on
    # ONE range with the auto-split actuator armed. The heat plane
    # advises a weighted-median split, the actuator executes it online
    # (writers keep committing through the epoch bump), and durable
    # QPS is sampled before/after the split lands.
    tmp = tempfile.mkdtemp(prefix="bench-range-autosplit-")
    srv = None
    routers = []
    heat = RangeHeatRecorder()
    heat.configure(enabled=True, bucket_seconds=1,
                   sustained_buckets=1, hot_ratio=1.5)
    heat.set_specs(split_keyspace(2))
    events = _obs.EventLog()
    try:
        srv = RangeServer(tmp, lease_ms=250, specs=split_keyspace(2),
                          sync_log="commit", heat=heat, events=events,
                          auto_split=True, split_cooldown_ms=0)
        tso = TimestampOracle()
        stop = threading.Event()
        counts = [0] * workers

        def hot_worker(w: int) -> None:
            router = RangeRouter(root=tmp)
            routers.append(router)
            committer = TwoPhaseCommitter(router, tso, lock_ttl=3000)
            i = 0
            while not stop.is_set():
                # every key inside one narrow band of range 1: the
                # classic hot-range shape the advisory targets
                key = b"\x10hot%04d" % ((w * 193 + i) % 512)
                committer.commit(
                    [Mutation(OP_PUT, key, b"v%d" % i)], tso.ts())
                counts[w] += 1
                i += 1

        threads = [threading.Thread(target=hot_worker, args=(w,),
                                    name=f"bench-autosplit-w{w}",
                                    daemon=True)
                   for w in range(workers)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        t_split = None
        pre_commits = 0
        deadline = t0 + seconds
        while time.perf_counter() < deadline:
            if t_split is None \
                    and len(srv.directory.load_specs()) >= 3:
                t_split = time.perf_counter()
                pre_commits = sum(counts)
            time.sleep(0.05)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        wall = time.perf_counter() - t0
        total = sum(counts)
        res["values"]["range_write_auto_splits"] = srv._auto_splits
        if t_split is not None:
            pre_qps = pre_commits / max(t_split - t0, 1e-9)
            post_qps = (total - pre_commits) / max(wall -
                                                   (t_split - t0), 1e-9)
            res["values"]["range_write_qps_hot_pre"] = round(pre_qps, 1)
            res["values"]["range_write_qps_hot_post"] = round(post_qps, 1)
            lines.append(
                f"range_write auto-split: hot band split after "
                f"{t_split - t0:.1f}s — {pre_qps:.0f} txn/s on the "
                f"single hot range, {post_qps:.0f} txn/s once the "
                f"actuator partitioned it")
            for e in events.snapshot():
                if e["kind"] == "range_split":
                    lines.append(f"range_write auto-split event: "
                                 f"{e['detail']}")
        else:
            # an all-identical-keys or too-short run legitimately
            # yields no advisory — report, don't fail the flight
            lines.append(
                f"range_write auto-split: actuator did not fire in "
                f"{wall:.1f}s ({total} hot commits)")
    finally:
        for router in routers:
            router.close()
        if srv is not None:
            srv.close()
        shutil.rmtree(tmp, ignore_errors=True)
    lines.append(
        f"range_write wait-profile cost: "
        f"{res['values']['range_write_wp_ratio']:.3f}x QPS with the "
        "typed wait ledger on (fresh ledger per txn) vs off")


FLIGHTS = {
    "tpch_small": lambda res: flight_tpch(res, big=False),
    "tpch_big": lambda res: flight_tpch(res, big=True),
    "joins": flight_joins,
    "ssb": flight_ssb,
    "cb": flight_cb,
    "multichip": flight_multichip,
    "replica_read": flight_replica_read,
    "htap_mixed": flight_htap_mixed,
    "range_write": flight_range_write,
}


def _inspection_snapshot() -> list:
    """One inspection pass over every live Storage the flight built
    (the obs_inspect weak registry): rule findings + the event-ring
    tail. Best effort — a post-mortem must never raise."""
    try:
        from tidb_tpu import obs_inspect
        return obs_inspect.inspect_all()
    except BaseException as e:  # noqa: BLE001 — diagnosis is optional
        return [{"error": f"{type(e).__name__}: {str(e)[:200]}"}]


def run_flight_child(name: str, out_path: str) -> None:
    res = {"ok": False, "lines": [], "values": {}}

    # periodic partial dump (atomic tmp+rename): a flight the parent
    # SIGKILLs at the timeout — or the OOM killer takes — leaves its
    # latest inspection snapshot in the result file, so rc=124/rc=137
    # rounds carry a diagnosis instead of just a heartbeat tail. The
    # lock + stop re-check keep a mid-cycle dump from clobbering the
    # FINAL result if its join below times out.
    stop = threading.Event()
    out_lock = threading.Lock()

    def _dump_partial() -> None:
        import copy

        while not stop.wait(30.0):
            try:
                # deep copy with a retry: the flight thread mutates
                # res["values"]/res["lines"] concurrently, and a
                # mid-iteration mutation raises RuntimeError — exactly
                # during the active phases this snapshot exists for
                for _ in range(3):
                    try:
                        snap = copy.deepcopy(res)
                        break
                    except RuntimeError:
                        continue
                else:
                    continue  # busy dict; catch it next cycle
                snap["ok"] = False
                snap["partial"] = True
                snap["inspection"] = _inspection_snapshot()
                tmp = out_path + ".part.tmp"
                with open(tmp, "w") as f:
                    json.dump(snap, f, default=str)
                with out_lock:
                    if stop.is_set():
                        os.unlink(tmp)
                        return  # the final result owns the file now
                    os.replace(tmp, out_path)
            except BaseException:  # noqa: BLE001 — keep flying
                pass

    dumper = threading.Thread(target=_dump_partial, daemon=True,
                              name="bench-inspection-dump")
    dumper.start()
    try:
        FLIGHTS[name](res)
        res["ok"] = True
    except BaseException as e:  # noqa: BLE001 - report, parent decides
        res["error"] = f"{type(e).__name__}: {str(e)[:300]}"
        res["inspection"] = _inspection_snapshot()
    finally:
        stop.set()
        dumper.join(timeout=2.0)
    with out_lock:
        # atomic like the periodic dumps: a kill landing mid-final-write
        # must not truncate away the last good partial snapshot
        tmp = out_path + ".final.tmp"
        with open(tmp, "w") as f:
            json.dump(res, f, default=str)
        os.replace(tmp, out_path)
    if not res["ok"]:
        log(f"flight {name} FAILED: {res.get('error')}")
        sys.exit(1)


# ---------------------------------------------------------------------------
# Round trajectory (ISSUE 15): compare this round against the previous
# committed BENCH_r*/MULTICHIP_r* record so a bench round produces a
# machine-read comparison, not just a JSON file nobody diffs.
# ---------------------------------------------------------------------------

_P50_RE = None  # compiled lazily


def parse_query_p50s(text: str) -> dict[str, float]:
    """Per-query p50 milliseconds from board text: every timed query
    reports through report() as '<name>: p50=NN.Nms ...', and the
    LEGACY round wrappers (r01..r06) carry the same lines in their
    stderr `tail` — one parser reads both eras."""
    import re
    global _P50_RE
    if _P50_RE is None:
        _P50_RE = re.compile(
            r"(?:^|\s)([A-Za-z_][\w.]*): p50=([0-9.]+)ms ")
    out: dict[str, float] = {}
    for m in _P50_RE.finditer(text):
        out[m.group(1)] = float(m.group(2))
    return out


def load_prev_round(prefix: str) -> tuple[int, Optional[dict]]:
    """Newest committed {prefix}_rNN.json next to this file ->
    (round_no, data); (0, None) when no round has ever landed."""
    import re
    here = os.path.dirname(os.path.abspath(__file__))
    best, data = 0, None
    for fn in sorted(os.listdir(here)):
        m = re.match(rf"{re.escape(prefix)}_r(\d+)\.json$", fn)
        if not m or int(m.group(1)) <= best:
            continue
        try:
            with open(os.path.join(here, fn)) as f:
                d = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        best, data = int(m.group(1)), d
    return best, data


def prev_round_p50s(data: Optional[dict]) -> dict[str, float]:
    """A previous round's per-query p50s: the structured `queries` map
    when the round wrote one (r07+), else parsed out of its board
    lines / stderr tail (the legacy wrapper format)."""
    if not isinstance(data, dict):
        return {}
    q = data.get("queries")
    if isinstance(q, dict):
        out = {}
        for k, v in q.items():
            try:
                out[str(k)] = float(v)
            except (TypeError, ValueError):
                continue
        return out
    text = "\n".join(str(ln) for ln in data.get("lines", []) or [])
    return parse_query_p50s(text + "\n" + str(data.get("tail", "")))


def compare_rounds(prev_no: int, prev_p50s: dict[str, float],
                   cur_p50s: dict[str, float],
                   ratio: float) -> dict:
    """The trajectory section: per-query prev/cur p50 + speedup, with
    regressions flagged by the SAME ratio knob the history plane's
    plan-regression rule uses (history.regression-ratio; env
    BENCH_REGRESSION_RATIO here — one threshold, two ends of the
    telemetry loop)."""
    deltas: dict[str, dict] = {}
    regressions: list[str] = []
    for name in sorted(set(cur_p50s) | set(prev_p50s)):
        cur = cur_p50s.get(name)
        prev = prev_p50s.get(name)
        if cur is None:
            # the worst regression of all: the query stopped producing
            # a number (flight died/timed out) — flag it, don't let it
            # vanish from the comparison
            regressions.append(name)
            deltas[name] = {"cur_ms": None, "prev_ms": prev,
                            "speedup": None, "regression": True}
            continue
        if prev is None or prev <= 0 or cur <= 0:
            deltas[name] = {"cur_ms": cur, "prev_ms": prev,
                            "speedup": None, "regression": False}
            continue
        speedup = prev / cur
        regressed = cur >= ratio * prev
        if regressed:
            regressions.append(name)
        deltas[name] = {"cur_ms": cur, "prev_ms": prev,
                        "speedup": round(speedup, 2),
                        "regression": regressed}
    return {"vs_round": prev_no, "regression_ratio": ratio,
            "deltas": deltas, "regressions": regressions}


def trajectory_lines(label: str, traj: dict) -> list[str]:
    """Board lines for one trajectory section, regressions loudest."""
    out = []
    if not traj["deltas"]:
        return [f"trajectory {label}: no comparable previous round"]
    for name, d in traj["deltas"].items():
        if d["cur_ms"] is None:
            out.append(
                f"trajectory {label} {name}: "
                f"{d['prev_ms']:.1f}ms -> MISSING (no result this "
                f"round) <- REGRESSION")
            continue
        if d["speedup"] is None:
            out.append(f"trajectory {label} {name}: {d['cur_ms']:.1f}ms "
                       "(new query, no r"
                       f"{traj['vs_round']:02d} point)")
            continue
        tag = " <- REGRESSION" if d["regression"] else ""
        out.append(
            f"trajectory {label} {name}: {d['prev_ms']:.1f}ms -> "
            f"{d['cur_ms']:.1f}ms ({d['speedup']:.2f}x vs "
            f"r{traj['vs_round']:02d}){tag}")
    if traj["regressions"]:
        out.append(
            f"trajectory {label}: {len(traj['regressions'])} "
            f"regression(s) >= {traj['regression_ratio']:g}x: "
            + ",".join(traj["regressions"]))
    return out


def _persist_round(path: str, payload: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1, default=str)
    os.replace(tmp, path)
    log(f"round record written: {path}")


# ---------------------------------------------------------------------------
# Parent board
# ---------------------------------------------------------------------------

def _headline(values: dict, baseline_rps: float, lines_done: int) -> str:
    big = bool(values.get("q6_big"))
    rps = values.get("q6_big") or values.get("q6_small") or 0.0
    rows = values.get("rows_big" if big else "rows_small", 0)
    return json.dumps({
        "metric": "tpch_q6_rows_per_sec",
        "value": round(rps),
        "unit": "rows/s",
        "vs_baseline": round(rps / baseline_rps, 2) if baseline_rps else
        None,
        "scale": f"sf{round(rows / ROWS_PER_SF, 2):g}" if rows else
        "unknown",
        "baseline": "compiled C++ row-loop (native/baseline.cpp), "
                    "single-stream",
        "flights_done": lines_done,
    })


def main() -> None:
    if len(sys.argv) >= 4 and sys.argv[1] == "--flight":
        run_flight_child(sys.argv[2], sys.argv[4] if sys.argv[3] == "--out"
                         else sys.argv[3])
        return

    # ---- parent: measure the compiled baseline first (numpy-only) ----
    # A baseline failure must never cost the round its headline (the
    # round-4 lesson, generalized): flights still run, vs_baseline is
    # null, and the error is on the board.
    kv_rps = col_rps = q1_rps = 0.0
    baseline_err = None
    t0 = time.perf_counter()
    try:
        from tidb_tpu.bench.tpch import generate_lineitem_arrays

        sample = generate_lineitem_arrays(6_000_000)
        kv_rps, col_rps, q1_rps = compiled_baselines(sample)
        del sample
        log(f"compiled baselines ({time.perf_counter() - t0:.0f}s): "
            f"q6-kv-rowloop={kv_rps / 1e6:.0f}M rows/s, "
            f"q6-columnar-rowloop={col_rps / 1e6:.0f}M rows/s, "
            f"q1-kv-rowloop={q1_rps / 1e6:.0f}M rows/s (C++ -O3, "
            f"single-stream, native/baseline.cpp)")
    except Exception as e:  # noqa: BLE001 - headline must survive
        # (Exception, not BaseException: Ctrl-C/SystemExit still exit)
        baseline_err = f"{type(e).__name__}: {str(e)[:200]}"
        log(f"compiled baseline FAILED: {baseline_err}")

    # tpch_big FIRST: the SF100 north-star flight gets the freshest
    # machine (PR 9's datagen cache bounds its RSS) instead of paying
    # for everything that ran before it — two rounds died before the
    # big flight ever started (r04 rc=137, r05 rc=124)
    flight_names = os.environ.get(
        "BENCH_FLIGHTS",
        "tpch_big,tpch_small,joins,ssb,cb,multichip,replica_read,"
        "htap_mixed,range_write"
    ).split(",")
    timeout = float(os.environ.get("BENCH_FLIGHT_TIMEOUT", 5400))
    values: dict = {}
    flight_results: dict[str, dict] = {}
    all_lines: list[str] = [
        f"baseline_c_q6_kv_rowloop: {kv_rps / 1e6:.0f}M rows/s",
        f"baseline_c_q6_columnar_rowloop: {col_rps / 1e6:.0f}M rows/s",
        f"baseline_c_q1_kv_rowloop: {q1_rps / 1e6:.0f}M rows/s",
    ] if baseline_err is None else [f"compiled baseline FAILED: "
                                    f"{baseline_err}"]
    done = 0
    for name in flight_names:
        name = name.strip()
        if name not in FLIGHTS:
            log(f"unknown flight {name!r}; skipping")
            continue
        out = tempfile.NamedTemporaryFile(
            suffix=f".{name}.json", delete=False)
        out.close()
        log(f"=== flight {name} ===")
        t0 = time.perf_counter()
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--flight",
                 name, "--out", out.name],
                stdout=sys.stderr, stderr=sys.stderr, timeout=timeout)
            rc = proc.returncode
        except subprocess.TimeoutExpired:
            rc = -1
            all_lines.append(f"flight {name} TIMED OUT after {timeout}s")
        try:
            with open(out.name) as f:
                res = json.load(f)
        except (OSError, json.JSONDecodeError):
            res = {"ok": False, "lines": [],
                   "error": f"no result file (rc={rc}"
                            f"{', likely OOM-killed' if rc == -9 else ''})"}
        os.unlink(out.name)
        flight_results[name] = res
        all_lines += res.get("lines", [])
        if res.get("ok"):
            values.update(res.get("values", {}))
            done += 1
        else:
            all_lines.append(
                f"flight {name} FAILED: {res.get('error', f'rc={rc}')}")
            # the child's (possibly partial) inspection snapshot: the
            # diagnosis rides the board, not just the result JSON
            for snap in res.get("inspection", []) or []:
                findings = snap.get("findings") or []
                if snap.get("error"):
                    all_lines.append(
                        f"flight {name} inspection: {snap['error']}")
                for fnd in findings[:8]:
                    all_lines.append(
                        f"flight {name} inspection: {fnd.get('rule')}"
                        f"[{fnd.get('severity')}] {fnd.get('item')} "
                        f"{fnd.get('value', '')} — "
                        f"{str(fnd.get('details', ''))[:160]}")
        log(f"flight {name}: {'ok' if res.get('ok') else 'FAILED'} "
            f"in {time.perf_counter() - t0:.0f}s")
        # incremental headline: supersedes earlier lines, survives any
        # later flight's death
        if values.get("q6_big") or values.get("q6_small"):
            print(_headline(values, kv_rps, done), flush=True)

    if values.get("py_baseline"):
        all_lines.append(
            f"baseline_py_rowloop: {values['py_baseline'] / 1e3:.0f}K "
            f"rows/s (r01-r04 series denominator; r04 headline would be "
            f"{(values.get('q6_big') or values.get('q6_small', 0)) / values['py_baseline']:.1f}x against it)")

    # ---- round trajectory: this round vs the previous committed one ----
    ratio = float(os.environ.get("BENCH_REGRESSION_RATIO", 1.5))
    cur_p50s = parse_query_p50s("\n".join(all_lines))
    prev_no, prev_data = load_prev_round("BENCH")
    traj = compare_rounds(prev_no, prev_round_p50s(prev_data),
                          cur_p50s, ratio)
    all_lines += trajectory_lines("bench", traj)
    mc_res = flight_results.get("multichip")
    mc_traj = None
    if mc_res is not None:
        mc_p50s = parse_query_p50s(
            "\n".join(str(ln) for ln in mc_res.get("lines", [])))
        mc_no, mc_prev = load_prev_round("MULTICHIP")
        mc_prev_p50s = prev_round_p50s(mc_prev)
        if not mc_prev_p50s:
            # legacy MULTICHIP wrappers carried no query lines of
            # their own; the paired BENCH round's board has them
            mc_no = prev_no
            mc_prev_p50s = {
                k: v for k, v in prev_round_p50s(prev_data).items()
                if k.startswith("multichip_")}
        mc_traj = compare_rounds(mc_no, mc_prev_p50s, mc_p50s, ratio)
        all_lines += trajectory_lines("multichip", mc_traj)

    for ln in all_lines:
        log(ln)
    headline_ok = bool(values.get("q6_big") or values.get("q6_small"))
    if headline_ok:
        print(_headline(values, kv_rps, done), flush=True)
    else:
        print(json.dumps({
            "metric": "tpch_q6_rows_per_sec", "value": 0,
            "unit": "rows/s", "vs_baseline": 0,
            "error": "no flight produced a headline"}), flush=True)

    # ---- round record (BENCH_ROUND=N): structured, comparator-ready ----
    # BENCH_r{N}.json + MULTICHIP_r{N}.json next to this file, written
    # atomically; the `queries`/`trajectory` sections are what the NEXT
    # round's comparator (and ROADMAP item 5's strategy learner) read,
    # so landing a round finally produces a machine-read comparison.
    round_no = os.environ.get("BENCH_ROUND")
    if round_no:
        here = os.path.dirname(os.path.abspath(__file__))
        n = int(round_no)
        cmd = " ".join(f"{k}={v}" for k, v in sorted(os.environ.items())
                       if k.startswith("BENCH_")) + " python bench.py"
        _persist_round(os.path.join(here, f"BENCH_r{n:02d}.json"), {
            "round": n, "cmd": cmd,
            "ok": headline_ok, "flights_done": done,
            "headline": json.loads(_headline(values, kv_rps, done)),
            "values": {k: (round(v, 3) if isinstance(v, float) else v)
                       for k, v in sorted(values.items())},
            "queries": cur_p50s,
            "trajectory": traj,
            "lines": all_lines,
        })
        if mc_res is not None:
            _persist_round(
                os.path.join(here, f"MULTICHIP_r{n:02d}.json"), {
                    "round": n,
                    "ok": bool(mc_res.get("ok")),
                    "n_devices": int(os.environ.get(
                        "BENCH_MESH_DEVICES", 8)),
                    "values": mc_res.get("values", {}),
                    "queries": parse_query_p50s(
                        "\n".join(str(ln)
                                  for ln in mc_res.get("lines", []))),
                    "trajectory": mc_traj,
                    "mesh": mc_res.get("mesh"),
                    "attribution": mc_res.get("attribution"),
                    "lines": mc_res.get("lines", []),
                    "error": mc_res.get("error"),
                })


if __name__ == "__main__":
    main()
