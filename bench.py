"""Benchmark: TPC-H Q6 (and Q1) end-to-end rows/sec on the TiTPU engine.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Protocol (BASELINE.md): the reference publishes no absolute numbers in-repo
and its Go toolchain isn't present here, so the comparison floor is a
row-at-a-time interpreted coprocessor baseline measured in-process — the
execution model of the reference's mocktikv interpreter (reference:
store/mockstore/mocktikv/cop_handler_dag.go:150, row loop over MVCC pairs)
— timed on a sample and scaled. vs_baseline = engine rows/s divided by
interpreter rows/s. The north star (BASELINE.json) asks for >= 10x.

Environment knobs: BENCH_ROWS (default SF1 = 6_001_215), BENCH_REPEAT.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


def interpreted_q6_baseline(arrays: dict[str, np.ndarray],
                            sample: int = 200_000) -> float:
    """Row-at-a-time interpreted Q6 (mocktikv-style) rows/sec.

    Median of 3 runs — a single pass is noisy (GC, turbo, co-tenants) and
    the ratio metric inherits that noise."""
    from tidb_tpu.types.value import parse_date

    n = min(sample, len(arrays["l_shipdate"]))
    ship = arrays["l_shipdate"][:n].tolist()
    disc = arrays["l_discount"][:n].tolist()
    qty = arrays["l_quantity"][:n].tolist()
    price = arrays["l_extendedprice"][:n].tolist()
    d1, d2 = parse_date("1994-01-01"), parse_date("1995-01-01")
    rates = []
    for _ in range(3):
        t0 = time.perf_counter()
        acc = 0
        for i in range(n):
            s = ship[i]
            if s >= d1 and s < d2:
                d = disc[i]
                if 5 <= d <= 7 and qty[i] < 2400:
                    acc += price[i] * d
        dt = time.perf_counter() - t0
        rates.append(n / dt)
    return sorted(rates)[1]


def main() -> None:
    n_rows = int(os.environ.get("BENCH_ROWS", 6_001_215))
    repeat = int(os.environ.get("BENCH_REPEAT", 5))
    platform = os.environ.get("BENCH_PLATFORM")
    if platform:
        # this image pre-imports jax at interpreter startup, so
        # JAX_PLATFORMS in the env is ignored; the config path still works
        import jax
        jax.config.update("jax_platforms", platform)

    from tidb_tpu.bench.tpch import (
        TPCH_Q1,
        TPCH_Q6,
        generate_lineitem_arrays,
        load_lineitem,
    )
    from tidb_tpu.session import Session

    session = Session()
    t0 = time.perf_counter()
    load_lineitem(session, n_rows)
    load_s = time.perf_counter() - t0

    arrays = generate_lineitem_arrays(n_rows)
    baseline_rps = interpreted_q6_baseline(arrays)

    # correctness gate before timing (digest vs vectorized oracle)
    from tidb_tpu.types.value import parse_date
    d1, d2 = parse_date("1994-01-01"), parse_date("1995-01-01")
    mask = ((arrays["l_shipdate"] >= d1) & (arrays["l_shipdate"] < d2)
            & (arrays["l_discount"] >= 5) & (arrays["l_discount"] <= 7)
            & (arrays["l_quantity"] < 2400))
    oracle = int((arrays["l_extendedprice"][mask].astype(np.int64)
                  * arrays["l_discount"][mask]).sum())
    got = session.query(TPCH_Q6)[0][0]  # also warms compile + device cache
    assert got is not None and got.unscaled == oracle, (
        f"Q6 digest mismatch: {got} vs {oracle}")

    def times(sql: str) -> list[float]:
        session.query(sql)  # warm
        ts = []
        for _ in range(repeat):
            t = time.perf_counter()
            session.query(sql)
            ts.append(time.perf_counter() - t)
        return sorted(ts)

    def throughput(sql: str, n_clients: int = 16, per: int = 3) -> float:
        """Aggregate rows/s with n concurrent sessions over one storage —
        the DB-server metric (reference serves many connections; dispatch
        round-trips overlap across clients even though a single stream
        serializes). Each thread runs its own Session against the shared
        store + coprocessor caches."""
        import threading

        from tidb_tpu.session import Session as S

        sessions = [S(session.storage, cop=session.cop)
                    for _ in range(n_clients)]
        for s in sessions:
            s.query(sql)  # warm every thread's plan path
        errs: list[BaseException] = []

        def run(s):
            try:
                for _ in range(per):
                    s.query(sql)
            except BaseException as e:  # surfaced after join
                errs.append(e)

        best = 0.0
        for _ in range(2):  # two passes; report steady-state (best)
            threads = [threading.Thread(target=run, args=(s,))
                       for s in sessions]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            dt = time.perf_counter() - t0
            if errs:
                raise errs[0]
            best = max(best, n_clients * per * n_rows / dt)
        return best

    q6_ts = times(TPCH_Q6)
    q1_ts = times(TPCH_Q1)
    q6_p50 = q6_ts[len(q6_ts) // 2]
    q1_p50 = q1_ts[len(q1_ts) // 2]
    n_clients = 16
    q6_tput = throughput(TPCH_Q6, n_clients=n_clients)

    print(json.dumps({
        "metric": "tpch_q6_rows_per_sec",
        "value": round(q6_tput),
        "unit": "rows/s",
        "vs_baseline": round(q6_tput / baseline_rps, 2),
    }))
    # context lines on stderr so the JSON line stays clean
    import sys
    print(
        f"# rows={n_rows} load={load_s:.1f}s "
        f"q6_p50={q6_p50*1e3:.1f}ms ({n_rows/q6_p50/1e6:.1f}M rows/s) "
        f"q1_p50={q1_p50*1e3:.1f}ms ({n_rows/q1_p50/1e6:.1f}M rows/s) "
        f"q6_throughput_{n_clients}clients={q6_tput/1e6:.1f}M rows/s "
        f"interp-baseline={baseline_rps/1e3:.0f}K rows/s "
        f"platform={__import__('jax').default_backend()}",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
